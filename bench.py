"""Benchmark: prompts/sec/chip on the perturbation-sweep scoring path.

BASELINE.json's metric, measured honestly:

- **Real-size model.** On an accelerator the bench scores through
  ``llama2_7b()`` at full size (6.74B params) with DYNAMIC int8 — per-token
  activation quantization + s8 x s8 MXU dots, the TPU-native analogue of
  the 8-bit mode the reference runs (compare_base_vs_instruct.py:431-435,
  BitsAndBytesConfig(load_in_8bit) = LLM.int8() vector-wise quantization).
  Weights are chain-PROGRAMMED (tools/chain7b.py) at identical matmul
  cost: per decode step the throughput is weight-independent, but the
  headline's decode LENGTH is content-dependent by design — the shipped
  digit early stop ends the confidence decode at the answer, so the sweep
  is measured over real-text responses whose answer lands at a
  representative position (see _production_chain). Random weights +
  FakeTokenizer remain the fallback (stop never arms, full budget paid).
  On CPU (smoke runs, no real chip) a 136M-param flagship config keeps
  the bench runnable; the JSON labels which config ran.

- **Verified timing.** Under the tunneled-axon dispatch path,
  ``jax.block_until_ready`` returns before the device finishes (measured:
  it "timed" 4096³ matmuls at 7,883 TFLOPS on a 197-TFLOP chip). The only
  trustworthy sync is a host-side read. So the bench runs R scoring
  iterations inside ONE jitted ``lax.scan`` (single dispatch, no per-iter
  tunnel latency) and times dispatch -> ``float(checksum)``, where the
  checksum sums every iteration's yes-probabilities — XLA cannot elide any
  iteration's forward, and the float() forces full completion.

- **MFU sanity gate.** Implied matmul FLOPS (utils/profiling.scoring_step_
  flops) divided by the chip's published peak for the mode's dot dtype
  (int8 peak = 2x bf16 for the dynamic mode) must be <= 100%; the bench
  ABORTS (exit 1) on a physically impossible number instead of reporting
  it. The gate is ARMED on unknown chips too: a device kind missing from
  the profiling table aborts (exit 1) unless ``--allow-ungated`` is passed
  explicitly — an un-gated number can never be recorded silently
  (VERDICT r2 weak #6).

- **The headline is the SWEEP PATH.** BASELINE.json's metric is
  "prompts/sec/chip on the perturbation sweep", so the primary JSON value
  is a real `run_perturbation_sweep` (grid -> manifest -> shared-prefix
  fused scoring -> D6 writes), not the isolated scoring step; the isolated
  in-scan step (which the MFU gate checks) is printed as a secondary
  comment line. vs_baseline compares against the first honest recording
  of the SWEEP-path definition (18.47 p/s, round 2, SCALE.md).

- **Cold start is measured, not suffered.** The bench enables the
  persistent XLA compile cache (utils/compile_cache.py) in a FRESH
  per-run directory, so the warmup sweep's compile cost is a true cold
  start; it then drops the engine and warms up again with the compile
  plan's executables already present — the steady state a restarted
  worker reaches by deserializing the persistent cache instead of
  recompiling (XLA compilation, not tracing, is what scales with model
  size). Both land in the headline JSON as ``cold_start_s`` /
  ``warm_start_s``; per-shape compile seconds and cache hit/miss
  counts print as comment lines. Pass ``--compile-cache-dir`` to reuse
  a directory across runs (cold_start_s then reflects whatever the
  disk already holds).

- **Variable-length mode.** The headline's cells are fixed-length by
  design (one bucket, compile-once timing); production grids are RAGGED
  (real rephrasings spread ~2-4x in tokenized length). The varlen mode
  draws per-cell lengths from the corpus distribution recorded in
  SCALE.md and scores the SAME grid twice — ragged scheduler ON
  (engine/scheduler.py: bucket ladder + slot refill + cross-cell prefix
  reuse) vs the legacy single-bucket baseline — reporting both rates,
  the ragged margin, and the scheduler's batch-occupancy % /
  padding-waste % counters under the headline JSON's "varlen" key.

- **Serve mode.** The online serving layer (lir_tpu/serve) measured as a
  service: an open-loop Poisson load driver (arrivals at 3x the offline
  rate, lengths from the SCALE.md deciles, ~25% duplicate re-asks)
  against `ScoringServer`, with a full offline `run_perturbation_sweep`
  over the IDENTICAL grid as the baseline. Goodput
  (completed-within-deadline/s), p50/p95/p99 latency, dedup hit rate,
  and the goodput-vs-offline ratio land under the headline JSON's
  "serve" key.

Prints ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

# First recorded value of this benchmark definition (llama-2-7b shapes,
# int8, seq 256, 10-token readout window, single v5e chip, in-scan timing
# with host-side checksum sync; measured 2026-07-30 in the original
# weight-only mode at batch 16: 26.247 prompts/s = 91.4 implied TFLOPS =
# 46.4% MFU of the v5e bf16 peak). vs_baseline tracks framework
# improvement since this first honest recording (dynamic int8 + batch 24
# later raised the measured value ~1.2x). Update deliberately, never
# silently.
BENCH_NOMINAL_7B = 26.247  # prompts/sec/chip (isolated scoring step)

# First honest recording of the SWEEP-PATH definition (round 2,
# tools/sweep_bench.py: full run_perturbation_sweep at 7B int8-dyn+kvq8,
# batch 48, 256-token bucket — SCALE.md "end-to-end sweep throughput").
# This is the primary metric's baseline; update deliberately, never
# silently.
BENCH_NOMINAL_7B_SWEEP = 18.47  # prompts/sec/chip (end-to-end sweep)

# CPU smoke nominals (flagship 136M config, fp32) — only used when no
# accelerator is present so the JSON stays comparable run-to-run.
BENCH_NOMINAL_CPU = 2.0
BENCH_NOMINAL_CPU_SWEEP = 1.0

# Sweep-path measurement shape: batch 40 is the measured sweet spot for
# the shared-prefix scoring path on a 16 GiB v5e (48 OOMs — the shared
# cache carries suffix + generation slack slots; SCALE.md r3). Like the
# isolated step, the sweep falls down the ladder on HBM exhaustion.
SWEEP_BATCHES_TPU = (40, 32, 24, 16, 8)
SWEEP_CELLS_TPU = 160
SWEEP_BATCHES_CPU = (4,)
SWEEP_CELLS_CPU = 8

# Variable-length sweep mode (the ragged scheduler's acceptance
# workload): per-cell rephrasing lengths are drawn by inverse-CDF from
# the corpus length distribution recorded in SCALE.md ("rephrasing
# length distribution" — deciles of rephrased-main length as a FRACTION
# of the fixed-length bench's bucket-sized text). The median 1.0x keeps
# the headline's 256-token bucket; the tails (0.30x..2.20x, the ~2-4x
# spread real rephrasings of one legal main show) spread cells over ~5
# ladder buckets, which is what the single-bucket baseline pads away.
VARLEN_FRAC_DECILES = (0.30, 0.42, 0.55, 0.68, 0.82, 1.00, 1.18, 1.40,
                       1.70, 2.20)
VARLEN_CELLS_TPU = 160
VARLEN_CELLS_CPU = 16
# CPU smoke scales words UP (the fixed smoke's 12-word texts all land in
# the smallest bucket, where ragged == baseline by construction).
VARLEN_WORDS_CPU = 48

# Serve mode (the online serving layer, lir_tpu/serve): an open-loop
# Poisson load driver over the SAME ragged grid the offline comparison
# sweeps — arrivals at SERVE_ARRIVAL_X times the measured offline rate
# (the server stays backlogged, so goodput measures service capacity,
# not the arrival process), per-cell lengths drawn from the SCALE.md
# decile table (VARLEN_FRAC_DECILES), and SERVE_DUP_FRAC duplicate
# re-asks of early cells appended late in the arrival order (the dedup
# cache's bread and butter: perturbation traffic re-asks near-identical
# questions constantly). Reported under the headline JSON's "serve" key:
# p50/p95/p99 latency, goodput, and goodput vs the offline sweep's
# throughput on the identical grid.
SERVE_ARRIVAL_X = 3.0
SERVE_DUP_FRAC = 0.25
SERVE_CELLS_CPU = 16  # 8-cell smoke is all boundary (linger + dup gaps)

# Prefix-heavy serve mode (--prefix-share): the production workload —
# millions of users scoring VARIATIONS of the same ~5 legal prompts — as
# an arrival process: `share` of Poisson arrivals append a short unique
# variation to one of PREFIX_BASES long legal-prompt bases (distinct
# content, so PR-3's exact-match dedup CANNOT serve them; only the radix
# prefix cache helps), the rest are unique full-length prompts. The
# identical arrival trace runs against a prefix-cache-OFF server (the
# PR-3 baseline) and a prefix-cache-ON server on separate engines;
# reported under the headline JSON's "prefix_serve" key:
# prefill_tokens_avoided (+ avoided_frac over the timed pass), radix hit
# rate, pages in use/evicted, goodput vs the baseline on the same trace,
# and parity_ok (per-request results bitwise-identical across the two).
PREFIX_BASES = 5
PREFIX_CELLS_CPU = 24
PREFIX_CELLS_TPU = 160
PREFIX_POOL_PAGES = 192  # 5 bases x ~256 tokens ~= 80 pages, 2x slack

SEQ = 256
NEW_TOKENS = 10  # MAX_LOOK_AHEAD: the positions the C13 readout consumes

# (batch, n_iters) candidates, largest batch first; on HBM exhaustion the
# bench falls back down the list. 7B int8 on v5e-1 (16 GB): params 6.3 GiB;
# the int8 KV cache (~70 MiB/row incl. XLA's while-loop layout copy)
# admits batch 48, the measured throughput knee; 64 OOMs (SCALE.md,
# 2026-07-30).
TPU_CANDIDATES = ((48, 4), (32, 6), (24, 6), (16, 8), (8, 8))
CPU_CANDIDATES = ((8, 2), (4, 2))


def _is_oom(err: Exception) -> bool:
    from lir_tpu.utils.profiling import is_oom_error

    return is_oom_error(err)


def _tools_on_path() -> None:
    """Make tools/ importable (chain7b, tiny_checkpoints, the shared
    registry-preset resolver in scale_validation)."""
    tools = Path(__file__).resolve().parent / "tools"
    if str(tools) not in sys.path:
        sys.path.insert(0, str(tools))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--allow-ungated", action="store_true",
                    help="report numbers even when the chip kind is missing "
                         "from the MFU peak table (default: abort)")
    ap.add_argument("--model", default="llama2_7b",
                    help="models.registry preset name for the accelerator "
                         "bench (default: llama2_7b, the cache-heaviest "
                         "MHA architecture = the headline; e.g. mistral_7b "
                         "for the GQA comparison)")
    ap.add_argument("--sweep-batches", default=None,
                    help="comma-separated sweep batch ladder override "
                         "(e.g. 48,40 for GQA models whose smaller KV "
                         "cache fits batch 48)")
    ap.add_argument("--no-kernels", action="store_true",
                    help="skip the per-phase kernel breakdown (prefill / "
                         "decode / readout implied TFLOPS + MXU-idle "
                         "fraction, profiling.KernelStats) and the CPU "
                         "interpret-mode kernel parity smoke (headline "
                         "key \"kernels\")")
    ap.add_argument("--no-varlen", action="store_true",
                    help="skip the variable-length sweep mode (corpus-"
                         "sampled prompt lengths, ragged scheduler vs "
                         "single-bucket baseline)")
    ap.add_argument("--no-serve", action="store_true",
                    help="skip the online-serving mode (open-loop "
                         "Poisson load driver over the continuous "
                         "batcher vs the offline sweep on one grid)")
    ap.add_argument("--prefix-share", type=float, default=0.8,
                    help="shared-prefix fraction for the prefix-heavy "
                         "serve mode: this fraction of Poisson arrivals "
                         "are variations of one of 5 long legal-prompt "
                         "bases, served with the cross-request radix "
                         "prefix cache ON vs the PR-3 exact-dedup "
                         "baseline on the identical trace (default 0.8; "
                         "headline key \"prefix_serve\")")
    ap.add_argument("--no-prefix-serve", action="store_true",
                    help="skip the prefix-heavy serve mode")
    ap.add_argument("--no-fleet", action="store_true",
                    help="skip the multi-model fleet mode (N-model "
                         "agreement sweep: streamed/cached fleet vs the "
                         "sequential drop-and-reload baseline on the "
                         "identical question waves; reports the swap-"
                         "hidden fraction, fleet p/s, and the within-"
                         "question kappa — headline key \"fleet\")")
    ap.add_argument("--no-observatory", action="store_true",
                    help="skip the reliability-observatory mode "
                         "(sustained mixed load on one fleet server: "
                         "fleet_score traffic + scheduled sentinel "
                         "sweeps + stats/metrics polling with tracing "
                         "ON, vs the identical client load with "
                         "observability OFF; asserts seeded drift is "
                         "caught within one window with zero clean-"
                         "window false alarms, per-window kappa "
                         "bitwise == within_group_kappa, and goodput "
                         ">= 0.95x the off baseline — headline key "
                         "\"observatory\")")
    ap.add_argument("--no-speculative", action="store_true",
                    help="skip the speculative-decode mode (identical "
                         "confidence-tail grid swept speculation-ON vs "
                         "OFF: >= 2x fewer decode dispatches per row on "
                         "the warm pass, per-cell results bitwise, CPU "
                         "interpret-mode kernel parity included — "
                         "headline key \"speculative\")")
    ap.add_argument("--no-cascade", action="store_true",
                    help="skip the cascade-prefill bench mode (the "
                         "shared-trunk grid swept cascade-ON vs OFF with "
                         "per-cell parity and the prefill-phase MFU / p-s "
                         "plateau gates asserted in-bench)")
    ap.add_argument("--no-cascade-decode", action="store_true",
                    help="skip the cascade-decode bench mode (shared-"
                         "trunk warm grid dispatched with the trunk-"
                         "aware decode splits ON vs OFF: decode-phase "
                         "attention HBM-bytes/row reduction >= 1.3x, "
                         "payloads argmax-identical cold and paged-"
                         "warm — headline key \"cascade_decode\")")
    ap.add_argument("--no-elastic", action="store_true",
                    help="skip the elastic-serving mode (3 replica "
                         "servers behind the failover router, 1 killed "
                         "mid-run: zero dropped/double-resolved, "
                         "goodput >= 0.6x after the kill and recovering "
                         "on rejoin, leased sweep accumulator bitwise "
                         "vs a static run — headline key \"elastic\")")
    ap.add_argument("--no-disagg", action="store_true",
                    help="skip the disaggregated-serving mode (one "
                         "prefill-heavy open-loop trace served "
                         "colocated vs 1 prefill + 2 decode replicas "
                         "with KV-page migration at equal chip count: "
                         "p99 interactive decode latency >= 1.3x "
                         "better disaggregated, zero dropped, "
                         "payloads bitwise across the two servers, "
                         "migration seconds hidden vs exposed — "
                         "headline key \"disagg\")")
    ap.add_argument("--no-memory", action="store_true",
                    help="skip the memory-governance mode (identical "
                         "grid swept unpressured vs with a seeded "
                         "mid-run hbm_squeeze shrinking the HBM "
                         "governor's budget: goodput >= 0.6x "
                         "unpressured, zero crashed dispatches, "
                         "degradation-ladder rung counters nonzero in "
                         "BOTH directions, per-cell rows bitwise — "
                         "headline key \"memory\")")
    ap.add_argument("--no-tiered", action="store_true",
                    help="skip the tiered-memory mode (a shared-prefix "
                         "working set ~3x the HBM page pool re-served "
                         "on the HBM -> host DRAM -> disk KV ladder "
                         "vs evict-and-recompute: warm goodput >= "
                         "1.3x, zero crashed dispatches, payloads "
                         "bitwise, and a kill/restart leg re-serving "
                         "the sentinel grid with >= 90% prefill "
                         "tokens avoided — headline key \"tiered\")")
    ap.add_argument("--no-streaming-stats", action="store_true",
                    help="skip the streaming-statistics mode (identical "
                         "grid swept twice: device accumulator -> CIs "
                         "with the row artifact OFF vs csv-write + "
                         "host reload baseline; asserts parity and "
                         "reports sweep+analysis wall-clock and host-"
                         "transferred bytes under the headline key "
                         "\"streaming_stats\")")
    ap.add_argument("--chaos", action="store_true",
                    help="also measure goodput UNDER a seeded fault "
                         "schedule (lir_tpu/faults: transient errors + "
                         "an injected hang + an injected-NaN row) vs "
                         "fault-free on the same grid — recovered_"
                         "dispatches, degraded_rows, stalls_detected, "
                         "rows_quarantined, and the goodput ratio land "
                         "under the headline JSON's \"chaos\" key (the "
                         "robustness cost, tracked like perf)")
    ap.add_argument("--compile-cache-dir", default=None,
                    help="persistent compile cache dir (default: a fresh "
                         "temp dir per run, so cold_start_s is a true "
                         "cold compile; pass a stable dir to measure "
                         "restart behavior across bench runs)")
    args = ap.parse_args()

    # Flag validation FIRST — a malformed ladder must abort before the
    # multi-minute param init and isolated-step measurement, not after.
    batch_override = None
    if args.sweep_batches:
        try:
            batch_override = tuple(int(b) for b in
                                   args.sweep_batches.split(","))
        except ValueError:
            batch_override = ()
        if not batch_override or any(b <= 0 for b in batch_override):
            print(f"BENCH ABORT: --sweep-batches {args.sweep_batches!r} "
                  "must be comma-separated positive ints (e.g. 48,40)",
                  file=sys.stderr)
            sys.exit(1)

    from lir_tpu.engine import generate, score
    from lir_tpu.models import decoder, quant
    from lir_tpu.utils import compile_cache, profiling

    cache_dir = args.compile_cache_dir or tempfile.mkdtemp(
        prefix="lir-bench-xla-")
    compile_cache.enable_persistent_cache(cache_dir)
    print(f"# persistent compile cache: {cache_dir}"
          + ("" if args.compile_cache_dir else " (fresh per run)"),
          file=sys.stderr)

    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"

    # Gate arming check FIRST — before any multi-minute 7B param init. A
    # device kind missing from the peak table means the MFU sanity gate
    # cannot run; a new TPU generation hitting this path is exactly where
    # unsynced timing (the round-1 failure mode) would otherwise sail
    # through un-gated.
    if (on_accel and profiling.chip_peak_flops(dev) is None
            and not args.allow_ungated):
        print(
            f"BENCH ABORT: device kind {getattr(dev, 'device_kind', '?')!r} "
            "is not in profiling.CHIP_PEAK_BF16_FLOPS, so the MFU sanity "
            "gate cannot run. Add the chip's peak to the table, or rerun "
            "with --allow-ungated to record an UNGATED number on purpose.",
            file=sys.stderr)
        sys.exit(1)

    if on_accel:
        import dataclasses

        # The shared preset resolver (tools/scale_validation.py): rejects
        # misspellings (listing the valid names), T5 presets, and class
        # names — one resolver for every tool that takes --model.
        _tools_on_path()
        from scale_validation import resolve_preset
        try:
            cfg0 = resolve_preset(args.model)
        except SystemExit as err:
            print(f"BENCH ABORT: {err}", file=sys.stderr)
            sys.exit(1)
        # int8 KV cache: half the cache HBM -> batch 48 fits (the knee);
        # decode attention runs s8 dots like the dynamic weight mode.
        cfg = dataclasses.replace(cfg0, kv_cache_int8=True)
        # Production-default content: chain-programmed weights at FULL
        # model-size matmul cost whose responses are real text (the
        # confidence answer completes just past the corpus-median decode
        # step), so the sweep measures the SHIPPED early-stop defaults
        # instead of the FakeTokenizer worst case. Falls back to random
        # weights + FakeTokenizer (stops silently off) if unavailable.
        # For tied-embedding presets the returned cfg is the chain-untied
        # variant (identical step timing; see _production_chain).
        orig_tied = cfg.tie_embeddings
        params, sweep_tok, expect_conf, answer_step, cfg = \
            _production_chain(cfg)
        if params is None:
            params = quant.random_quantized_params(
                cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16,
                dynamic=True)
        candidates = TPU_CANDIDATES
        nominal = BENCH_NOMINAL_7B
        mode = "int8-dyn+kvq8" + ("+chain-untied-head"
                                  if sweep_tok is not None and orig_tied
                                  else "")
    else:
        from __graft_entry__ import _flagship_cfg
        cfg = _flagship_cfg()
        params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)
        candidates = CPU_CANDIDATES
        nominal = BENCH_NOMINAL_CPU
        mode = "fp32"
        sweep_tok, expect_conf, answer_step = None, None, None

    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, quant.QuantTensor))
        if not isinstance(l, quant.QuantTensor)
    ) + sum(
        int(np.prod(l.q.shape)) for l in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, quant.QuantTensor))
        if isinstance(l, quant.QuantTensor)
    )

    rng = np.random.default_rng(0)
    digit_ids = jnp.arange(10, 110, dtype=jnp.int32)
    digit_vals = jnp.arange(0, 100, dtype=jnp.float32)

    def build_program(batch: int, n_iters: int):
        """R scoring iterations in one jitted scan; returns a checksum that
        depends on every iteration's readout (nothing can be elided)."""
        toks = jnp.asarray(
            rng.integers(3, cfg.vocab_size, (n_iters, batch, SEQ)), jnp.int32)
        mask = jnp.ones((batch, SEQ), jnp.int32)
        yes_ids = jnp.full((batch,), 1, jnp.int32)
        no_ids = jnp.full((batch,), 2, jnp.int32)

        def one_iter(params, acc, iter_toks):
            fused = generate.greedy_decode_fused(
                params, cfg, iter_toks, mask, yes_ids, no_ids, digit_ids,
                digit_vals, max_new_tokens=NEW_TOKENS)
            res = score.readout_from_fused(fused, yes_ids, no_ids)
            acc = acc + jnp.sum(res.yes_prob) + jnp.sum(res.no_prob)
            return acc, None

        # params MUST be a traced argument: closing over a 7B tree would
        # constant-fold the weights into the HLO and stall compilation.
        def program(params, toks):
            acc, _ = jax.lax.scan(
                lambda a, t: one_iter(params, a, t), jnp.float32(0.0), toks)
            return acc

        return jax.jit(program), toks

    value = 0.0
    batch_used = candidates[-1][0]
    implied_tflops = 0.0
    mfu = None
    peak = (profiling.chip_peak_flops(dev, int8=mode.startswith("int8-dyn"))
            if on_accel else None)

    def _time_program(program, toks, batch):
        t_c = time.perf_counter()
        chk = float(program(params, toks))  # compile+warmup, host-read sync
        print(f"# bench: batch={batch} compile+first run "
              f"{time.perf_counter() - t_c:.1f}s", file=sys.stderr)
        if not np.isfinite(chk):
            raise RuntimeError(f"non-finite bench checksum: {chk}")
        best_dt = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            chk = float(program(params, toks))  # dispatch -> host read
            best_dt = min(best_dt, time.perf_counter() - t0)
        if not np.isfinite(chk):
            raise RuntimeError(f"non-finite bench checksum: {chk}")
        return best_dt

    last_oom = None
    fused_fallback = None
    for batch, n_iters in candidates:
        program, toks = build_program(batch, n_iters)
        try:
            try:
                best_dt = _time_program(program, toks, batch)
            except Exception as err:  # noqa: BLE001 — fused-kernel ladder
                if (not _is_oom(err) and on_accel
                        and getattr(cfg, "fused_decode", False)):
                    # Defensive ladder: a fused flash-decode failure on a
                    # new chip/toolchain must not kill the bench — retry
                    # this candidate on the dense decode path and record
                    # the fallback in the headline rather than aborting.
                    print(f"# fused-decode fallback: {err!r}; retrying "
                          "this batch with --no-fused-decode semantics",
                          file=sys.stderr)
                    import dataclasses as _dc
                    cfg = _dc.replace(cfg, fused_decode=False)
                    fused_fallback = repr(err)[:200]
                    program, toks = build_program(batch, n_iters)
                    best_dt = _time_program(program, toks, batch)
                else:
                    raise
        except Exception as err:  # noqa: BLE001 — OOM falls back, rest aborts
            if _is_oom(err):
                last_oom = err
                continue
            raise
        value = batch * n_iters / best_dt
        batch_used = batch
        step_flops = profiling.scoring_step_flops(cfg, batch, SEQ, NEW_TOKENS)
        implied_tflops = step_flops * n_iters / best_dt / 1e12
        if peak is not None:
            mfu = implied_tflops * 1e12 / peak
            if mfu > 1.0:
                print(
                    f"BENCH ABORT: implied {implied_tflops:.1f} TFLOPS is "
                    f"{mfu:.0%} of the {dev.device_kind} peak "
                    f"({peak / 1e12:.0f} TFLOPS) — timing is not syncing with "
                    f"the device; refusing to report an impossible number.",
                    file=sys.stderr)
                sys.exit(1)
        break
    else:
        print(f"BENCH ABORT: every batch candidate OOMed; last: {last_oom}",
              file=sys.stderr)
        sys.exit(1)

    if mfu is not None:
        mfu_str = f"{mfu:.1%} MFU"
    elif on_accel:
        mfu_str = "MFU UNGATED (unknown chip, --allow-ungated)"
    else:
        mfu_str = "MFU n/a (cpu)"
    print(f"# isolated scoring step: {value:.3f} prompts/s "
          f"(batch={batch_used}, {implied_tflops:.1f} TFLOPS impl, "
          f"{mfu_str}, vs r1-nominal {value / nominal:.3f}x)",
          file=sys.stderr)

    # Per-phase kernel breakdown + CPU interpret-mode kernel smoke
    # (headline key "kernels"). A failure here never discards the
    # already-measured headline.
    kernels = None
    if not args.no_kernels:
        try:
            kernels = _kernel_bench(params, cfg, batch_used, on_accel, peak)
            if "decode" in kernels:
                d = kernels["decode"]
                print(f"# kernel phases: decode {d['seconds']*1e3:.1f}ms "
                      f"{d['implied_tflops']:.1f} TFLOPS impl"
                      + (f" ({d['mfu']:.1%} MFU, idle {d['mxu_idle_frac']:.1%})"
                         if "mfu" in d else ""),
                      file=sys.stderr)
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# kernel bench mode failed ({err!r}); headline is "
                  "unaffected", file=sys.stderr)

    # ---- primary: the end-to-end perturbation sweep (BASELINE's metric).
    sweep_value, sweep_batch, sweep_cells, compile_stats = _sweep_path(
        params, cfg, on_accel, tokenizer=sweep_tok, expect_conf=expect_conf,
        batches=batch_override)
    # Provenance derives from the chain's OWN constants (returned by
    # _production_chain, owned by tools/chain7b.py) — changing the
    # answer step or value there can never silently desync this string
    # from what the programmed weights emit (ADVICE r5, bench.py:133).
    stop_str = ("confidence digit stop + binary EOS stop ON over "
                "real-text responses (production default; real BPE "
                "tokenizer, programmed-chain weights at identical matmul "
                f"cost, answer at decode step {answer_step} — "
                "conservatively past the corpus-median position 0-1, "
                "at the p90 bound, SCALE.md; stop-OFF worst "
                "case printed as a comment)" if sweep_tok is not None
                else "early stops OFF (content-free fallback)")
    sweep_nominal = (BENCH_NOMINAL_7B_SWEEP if on_accel
                     else BENCH_NOMINAL_CPU_SWEEP)
    arch_note = ("; headline is the cache-heaviest MHA architecture — "
                 "see SCALE.md for the faster GQA alternatives"
                 if cfg.name == "llama-2-7b" else
                 "; vs_baseline is vs the llama-2-7b r2 sweep nominal — a "
                 "cross-architecture ratio, not framework gain"
                 if on_accel else "")
    # Variable-length mode (corpus-sampled prompt lengths): runs BEFORE
    # the headline print so its result can ride the one JSON line, but a
    # failure here never discards the already-measured headline.
    varlen = None
    if not args.no_varlen:
        try:
            varlen = _varlen_sweep(params, cfg, on_accel,
                                   tokenizer=sweep_tok,
                                   expect_conf=expect_conf,
                                   batches=batch_override)
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# varlen sweep mode failed ({err!r}); fixed-length "
                  "headline is unaffected", file=sys.stderr)
    headline = {
        "metric": "sweep_prompts_per_sec_per_chip",
        "value": round(sweep_value, 3),
        "unit": (f"prompts/s end-to-end perturbation sweep ({cfg.name} "
                 f"{n_params / 1e9:.2f}B {mode}, shared-prefix scoring, "
                 f"batch={sweep_batch}, {sweep_cells} cells, "
                 f"binary+confidence per cell, {stop_str}; isolated step "
                 f"{value:.1f} p/s at {mfu_str}{arch_note}; "
                 f"{dev.platform})"),
        "vs_baseline": round(sweep_value / sweep_nominal, 3),
        # Cold start as a managed artifact: warmup wall time with an empty
        # vs warmed persistent compile cache (the restart/autoscale tax
        # the compile plan exists to eliminate — see _sweep_path).
        "cold_start_s": round(compile_stats.cold_start_s, 3),
        "warm_start_s": round(compile_stats.warm_start_s, 3),
    }
    if kernels is not None:
        headline["kernels"] = kernels
    if fused_fallback is not None:
        headline["fused_decode_fallback"] = fused_fallback
    if varlen is not None:
        headline["varlen"] = varlen
    # Streaming-statistics mode (ROADMAP item 4): grid -> CIs as one
    # device pipeline (row artifact OFF) vs the csv-write + host-reload
    # baseline on the IDENTICAL grid. Asserts streaming == reloaded
    # (counts/kappa bitwise) before reporting; a failure never discards
    # the already-measured headline.
    if not args.no_streaming_stats:
        try:
            streaming = _stream_stats_bench(params, cfg, on_accel,
                                            tokenizer=sweep_tok,
                                            batches=batch_override)
            if streaming is not None:
                headline["streaming_stats"] = streaming
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# streaming stats mode failed ({err!r}); headline "
                  "is unaffected", file=sys.stderr)
    # Serve mode (online serving layer): open-loop Poisson load against
    # the continuous batcher, with an offline sweep over the identical
    # grid as the goodput baseline. Like varlen, a failure here never
    # discards the already-measured headline.
    serve = None
    if not args.no_serve:
        try:
            serve = _serve_bench(params, cfg, on_accel,
                                 tokenizer=sweep_tok,
                                 expect_conf=expect_conf,
                                 batches=batch_override)
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# serve bench mode failed ({err!r}); headline is "
                  "unaffected", file=sys.stderr)
    if serve is not None:
        headline["serve"] = serve
    # Prefix-heavy serve mode: the production "variations of ~5 legal
    # prompts" arrival process with the cross-request radix prefix cache
    # ON vs the exact-dedup-only baseline on the identical trace. Like
    # serve, a failure here never discards the measured headline.
    if not args.no_prefix_serve:
        try:
            prefix_serve = _prefix_serve_bench(
                params, cfg, on_accel, tokenizer=sweep_tok,
                share=args.prefix_share, batches=batch_override)
            if prefix_serve is not None:
                headline["prefix_serve"] = prefix_serve
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# prefix serve mode failed ({err!r}); headline is "
                  "unaffected", file=sys.stderr)
    # Fleet mode (ROADMAP item 3): the N-model agreement workload —
    # every question wave scored under ALL fleet models — measured with
    # the streamed/cached fleet vs the sequential drop-and-reload
    # baseline (one model resident at a time, reload per switch: the
    # pre-fleet engine/serve reality). Asserts per-model score parity
    # bitwise before reporting; a failure never discards the headline.
    if not args.no_fleet:
        try:
            fleet = _fleet_bench(on_accel)
            if fleet is not None:
                headline["fleet"] = fleet
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# fleet bench mode failed ({err!r}); headline is "
                  "unaffected", file=sys.stderr)
    # Observatory mode (ROADMAP item 5): sustained mixed load — client
    # fleet_score traffic + scheduled sentinel sweeps + stats/metrics
    # polling + tracing on ONE fleet server — with a seeded drift
    # injection that must be caught within one window, zero
    # clean-window false alarms, per-window kappa bitwise equal to the
    # analysis layer, and observability overhead bounded (goodput >=
    # 0.95x the observability-off baseline). Failures never discard
    # the headline.
    if not args.no_observatory:
        try:
            observatory = _observatory_bench(on_accel)
            if observatory is not None:
                headline["observatory"] = observatory
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# observatory bench mode failed ({err!r}); headline "
                  "is unaffected", file=sys.stderr)
    # Elastic mode (ROADMAP item 1): 3 replica servers behind the
    # failover router with 1 killed mid-run — zero requests dropped or
    # double-resolved, goodput degrades proportionally to the capacity
    # lost (>= 0.6x of 3-replica goodput) and recovers when the
    # replica rejoins; plus the leased offline sweep whose kill/steal
    # resume converges BITWISE on an uninterrupted static-shard run.
    # Failures never discard the headline.
    if not args.no_elastic:
        try:
            elastic = _elastic_bench(on_accel)
            if elastic is not None:
                headline["elastic"] = elastic
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# elastic bench mode failed ({err!r}); headline "
                  "is unaffected", file=sys.stderr)
    # Disaggregated mode (ROADMAP item 2): the prefill-heavy trace
    # served colocated vs prefill/decode-split at equal chip count —
    # p99 interactive decode latency >= 1.3x better disaggregated,
    # payloads bitwise, nonzero pages migrated. Failures never discard
    # the headline.
    if not args.no_disagg:
        try:
            disagg = _disagg_bench(on_accel)
            if disagg is not None:
                headline["disagg"] = disagg
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# disagg bench mode failed ({err!r}); headline "
                  "is unaffected", file=sys.stderr)
    # Speculative mode (ROADMAP item 3): the identical grid swept
    # speculation-ON vs OFF — >= 2x fewer decode dispatches per row on
    # the warm (prompt-lookup-drafted) pass, per-cell results bitwise,
    # interpret-mode verify-kernel parity included. Failures never
    # discard the headline.
    if not args.no_speculative:
        try:
            speculative = _spec_bench(on_accel)
            if speculative is not None:
                headline["speculative"] = speculative
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# speculative bench mode failed ({err!r}); headline "
                  "is unaffected", file=sys.stderr)
    # Cascade mode (ROADMAP item 1): the shared-trunk grid — every
    # rephrasing sharing one long legal trunk, the paper's axis-1
    # workload — swept cascade-ON vs OFF. Per-cell parity at the PR-7
    # bar, nonzero trunk prefills deduped, and the implied
    # prefill-phase MFU / p-s above the 36% / ~41 p/s plateau are
    # asserted in-bench. Failures never discard the headline.
    if not args.no_cascade:
        try:
            cascade = _cascade_bench(on_accel)
            if cascade is not None:
                headline["cascade"] = cascade
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# cascade bench mode failed ({err!r}); headline "
                  "is unaffected", file=sys.stderr)
    # Cascade-DECODE mode (PR 17): the shared-trunk warm grid's decode
    # phase with the trunk-aware flash-decode splits ON vs OFF —
    # attention HBM-bytes/row reduction >= 1.3x (analytic, mirroring
    # the kernel's own split ladder), payloads argmax-identical cold
    # and paged-warm. Failures never discard the headline.
    if not args.no_cascade_decode:
        try:
            cascade_decode = _cascade_decode_bench(on_accel)
            if cascade_decode is not None:
                headline["cascade_decode"] = cascade_decode
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# cascade-decode bench mode failed ({err!r}); "
                  "headline is unaffected", file=sys.stderr)
    # Memory-governance mode: the identical grid swept unpressured vs
    # under a seeded mid-run hbm_squeeze (engine/hbm.py degradation
    # ladder) — the memory-robustness cost tracked like perf. Failures
    # never discard the headline.
    if not args.no_memory:
        try:
            memory = _memory_bench(on_accel)
            if memory is not None:
                headline["memory"] = memory
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# memory bench mode failed ({err!r}); headline "
                  "is unaffected", file=sys.stderr)
    # Tiered-memory mode (serve/tiers.py): the working-set-3x-HBM grid
    # re-served on the KV ladder vs evict-and-recompute, plus the
    # restart-warm leg — the capacity-robustness win tracked like perf.
    # Failures never discard the headline.
    if not args.no_tiered:
        try:
            tiered = _tiered_bench(on_accel)
            if tiered is not None:
                headline["tiered"] = tiered
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# tiered bench mode failed ({err!r}); headline "
                  "is unaffected", file=sys.stderr)
    # Chaos mode (--chaos): the same serving layer under a seeded
    # transient fault schedule — the robustness cost (recovery work +
    # goodput delta) tracked alongside perf. Failures never discard the
    # already-measured headline.
    if args.chaos:
        try:
            chaos = _chaos_bench(params, cfg, on_accel,
                                 tokenizer=sweep_tok,
                                 batches=batch_override)
            if chaos is not None:
                headline["chaos"] = chaos
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# chaos bench mode failed ({err!r}); headline is "
                  "unaffected", file=sys.stderr)
    print(json.dumps(headline))
    if sweep_tok is not None:
        # Transparency: the content-free worst case (FakeTokenizer exposes
        # no per-token strings, so the digit stop cannot arm and every
        # confidence cell pays the full 8-step budget). Runs AFTER the
        # headline JSON so a failure here can never discard the
        # already-measured production result.
        try:
            nostop_value, nostop_batch, _, _ = _sweep_path(
                params, cfg, on_accel, batches=batch_override)
            print(f"# sweep stop-OFF worst case (FakeTokenizer, batch "
                  f"{nostop_batch}): {nostop_value:.3f} p/s",
                  file=sys.stderr)
        except (Exception, SystemExit) as err:  # noqa: BLE001
            print(f"# stop-OFF transparency run failed ({err!r}); "
                  "headline above is unaffected", file=sys.stderr)


def _kernel_interp_smoke() -> dict:
    """CPU proof that the PR-7 fused paths run and agree with the paths
    they replace: the flash-decode kernel under the Pallas interpreter
    (the tier-1 hook, models/decoder.FUSED_DECODE_INTERPRET_ON_CPU) must
    decode argmax-identical to the dense path, and a piggybacked
    dispatch pair must reproduce the sequential dispatches per row."""
    from lir_tpu.engine import generate
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    import dataclasses as _dc

    cfg = ModelConfig(name="kernel-smoke", vocab_size=256, hidden_size=32,
                      n_layers=2, n_heads=4, n_kv_heads=2,
                      intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(3, 256, (2, 16)), jnp.int32)
    mask = jnp.ones((2, 16), jnp.int32)
    gen_d, _ = generate.greedy_decode(params, cfg, toks, mask,
                                      max_new_tokens=4)
    # A distinct cfg name forces a fresh trace under the interpret hook
    # (the routing is baked at trace time).
    old = decoder.FUSED_DECODE_INTERPRET_ON_CPU
    decoder.FUSED_DECODE_INTERPRET_ON_CPU = True
    try:
        gen_f, _ = generate.greedy_decode(
            params, _dc.replace(cfg, name="kernel-smoke-fused"), toks,
            mask, max_new_tokens=4)
    finally:
        decoder.FUSED_DECODE_INTERPRET_ON_CPU = old
    fused_ok = bool((np.asarray(gen_d) == np.asarray(gen_f)).all())

    prefix = jnp.asarray(rng.integers(3, 256, (2, 16)), jnp.int32)
    pm = jnp.ones((2, 16), jnp.int32)
    sfx_a = jnp.asarray(rng.integers(3, 256, (2, 4)), jnp.int32)
    sam = jnp.ones((2, 4), jnp.int32)
    sfx_b = jnp.asarray(rng.integers(3, 256, (2, 8)), jnp.int32)
    sbm = jnp.ones((2, 8), jnp.int32)
    yes = jnp.asarray([5, 6], jnp.int32)
    no = jnp.asarray([9, 10], jnp.int32)
    d_ids = jnp.arange(10, 30, dtype=jnp.int32)
    d_vals = jnp.arange(0.0, 20.0, dtype=jnp.float32)
    args = (prefix, pm, sfx_a, sam, sfx_b, sbm)
    seq = generate.greedy_decode_fused_shared(
        params, cfg, *args, yes, no, d_ids, d_vals, max_new_a=3,
        max_new_b=5)
    carry = generate.shared_piggyback_prefill(params, cfg, *args,
                                              max_new_a=3, max_new_b=5)
    pig = generate.shared_piggyback_drain(
        params, cfg, carry, yes, no, d_ids, d_vals, slot0_a=16 + 4,
        slot0_b=16 + 4 + 3 + 8, max_new_a=3, max_new_b=5)
    piggy_ok = True
    for s, p in zip(jax.tree.leaves(seq), jax.tree.leaves(pig)):
        s, p = np.asarray(s), np.asarray(p)
        if np.issubdtype(s.dtype, np.floating):
            piggy_ok &= bool(np.allclose(s, p, atol=1e-5))
        else:
            piggy_ok &= bool((s == p).all())

    # Cascade parity: the shared-trunk decomposition (prefix leg once at
    # batch 1 + per-row suffix leg, merged by ops/lse — the
    # ops/cascade_prefill kernel under the Pallas interpreter) must match
    # the dense shared path on a batch whose rows share a verbatim trunk:
    # generated ids exact, floats within tolerance (the log-sum-exp
    # reduction order differs, so interior floats are tolerance-bound).
    trunk_len = 16
    head = jnp.asarray(rng.integers(3, 256, (1, trunk_len)), jnp.int32)
    tails = jnp.asarray(rng.integers(3, 256, (2, 8)), jnp.int32)
    cprefix = jnp.concatenate([jnp.tile(head, (2, 1)), tails], axis=1)
    cpm = jnp.ones((2, trunk_len + 8), jnp.int32)
    cargs = (cprefix, cpm, sfx_a, sam, sfx_b, sbm)
    seq_c = generate.greedy_decode_fused_shared(
        params, cfg, *cargs, yes, no, d_ids, d_vals, max_new_a=3,
        max_new_b=5)
    casc = generate.greedy_decode_fused_shared_cascade(
        params, cfg, *cargs, yes, no, d_ids, d_vals, max_new_a=3,
        max_new_b=5, trunk_len=trunk_len)
    cascade_ok = True
    for s, c in zip(jax.tree.leaves(seq_c), jax.tree.leaves(casc)):
        s, c = np.asarray(s), np.asarray(c)
        if np.issubdtype(s.dtype, np.floating):
            cascade_ok &= bool(np.allclose(s, c, atol=5e-5))
        else:
            cascade_ok &= bool((s == c).all())
    return {"fused_decode_interpret_ok": fused_ok,
            "piggyback_interpret_ok": piggy_ok,
            "cascade_interpret_ok": cascade_ok}


def _kernel_bench(params, cfg, batch: int, on_accel: bool,
                  peak) -> dict:
    """Per-phase MFU breakdown of the isolated scoring step
    (profiling.KernelStats — ROADMAP item 2: the plateau must be
    measurable per COMPONENT): prefill / decode / readout seconds and
    implied TFLOPS against the analytic scoring_step_flops_split, with
    the MXU-idle fraction per phase when the chip's peak is known. The
    readout (lm_head) is timed standalone and its per-step cost
    subtracted out of the prefill/decode rows, so the decode row
    isolates exactly the KV-cached layer scan the fused flash-decode
    kernel attacks."""
    from lir_tpu.engine import generate
    from lir_tpu.models import decoder
    from lir_tpu.utils import profiling

    stats = profiling.KernelStats()
    rng = np.random.default_rng(7)
    toks = jnp.asarray(rng.integers(3, cfg.vocab_size, (batch, SEQ)),
                       jnp.int32)
    mask = jnp.ones((batch, SEQ), jnp.int32)
    yes_ids = jnp.full((batch,), 1, jnp.int32)
    no_ids = jnp.full((batch,), 2, jnp.int32)
    digit_ids = jnp.arange(10, 110, dtype=jnp.int32)
    digit_vals = jnp.arange(0, 100, dtype=jnp.float32)
    T = SEQ + NEW_TOKENS

    prefill_fn = jax.jit(lambda p, t, m: decoder.prefill(p, cfg, t, m, T)[0])
    dt = jax.tree.leaves(params)[0].dtype
    x_ro = jnp.asarray(rng.normal(size=(batch, 1, cfg.hidden_size)), dt)
    readout_fn = jax.jit(lambda p, x: decoder._unembed(p, cfg, x))

    def timed(fn) -> float:
        jax.block_until_ready(fn())   # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            best = min(best, time.perf_counter() - t0)
        return best

    t_ro = timed(lambda: readout_fn(params, x_ro))
    t_prefill = timed(lambda: prefill_fn(params, toks, mask))
    t_full = timed(lambda: generate.greedy_decode_fused(
        params, cfg, toks, mask, yes_ids, no_ids, digit_ids, digit_vals,
        max_new_tokens=NEW_TOKENS).p_yes)

    split = profiling.scoring_step_flops_split(cfg, batch, SEQ, NEW_TOKENS)
    eps = 1e-9
    stats.record_phase("prefill", max(t_prefill - t_ro, eps),
                       split["prefill"], peak)
    stats.record_phase("decode",
                       max(t_full - t_prefill - NEW_TOKENS * t_ro, eps),
                       split["decode"], peak)
    stats.record_phase("readout", (1 + NEW_TOKENS) * t_ro,
                       split["readout"], peak)
    out = stats.summary()
    if not on_accel:
        out.update(_kernel_interp_smoke())
    out["fused_decode"] = bool(getattr(cfg, "fused_decode", False)
                               and on_accel)
    return out


def _production_chain(cfg):
    """Chain-programmed params at the FULL flagship size (tools/chain7b:
    zero attention/MLP at full matmul cost, one-hot embeddings, lm_head
    transition table — throughput-identical to random weights) plus the
    offline-trained byte-BPE tokenizer. Responses are real text: the
    binary prompt answers ' Yes.', the confidence prompt emits its
    single-token integer (chain7b.CHAIN_CONFIDENCE_VALUE) at decode step
    CHAIN_ANSWER_STEP — one-two steps LATER
    than the corpus-median answer word position of 0-1 (SCALE.md
    "confidence decode budget"), i.e. a conservative stop point: a real
    checkpoint answering at the median refunds MORE budget than this
    measurement claims. The stop then arms exactly as shipped
    (`sweep_early_stop` default). Returns (params, tokenizer,
    expected_confidence, answer_step, cfg_to_use) — the middle two are
    chain7b's CHAIN_CONFIDENCE_VALUE / CHAIN_ANSWER_STEP, cfg_to_use is
    the chain-untied variant for tied-embedding presets — or
    (None, None, None, None, cfg) for the content-free fallback."""
    try:
        import dataclasses

        _tools_on_path()
        import jax as _jax
        from chain7b import (CHAIN_CONFIDENCE_FORMAT, CHAIN_RESPONSE_FORMAT,
                             confidence_chain, ship_quantized_chain)
        from tiny_checkpoints import build_bpe_tokenizer

        # Tied-embedding presets (falcon, bloom, gpt2 family): a symmetric
        # W W^T head cannot encode an asymmetric t -> next(t) table, so
        # the chain INSTRUMENT unties the head. Per-step timing is
        # identical (same matmul, same per-step weight read — sharing only
        # changes aliasing), so the measured number is what a real TIED
        # checkpoint does in production, where the stops arm on real
        # weights without any instrument.
        chain_cfg = (dataclasses.replace(cfg, tie_embeddings=False)
                     if cfg.tie_embeddings else cfg)
        fast = build_bpe_tokenizer()
        # answer step + confidence value come from chain7b's OWN
        # constants, and are returned so the headline provenance string
        # and the per-row assertion can never desync from the weights.
        chain, junk_next, junk_second = confidence_chain(
            fast, CHAIN_RESPONSE_FORMAT, CHAIN_CONFIDENCE_FORMAT)
        params = ship_quantized_chain(_jax, _jax.devices()[0], chain_cfg,
                                      chain, junk_next=junk_next,
                                      junk_second=junk_second)
        from chain7b import CHAIN_ANSWER_STEP, CHAIN_CONFIDENCE_VALUE
        return (params, fast, CHAIN_CONFIDENCE_VALUE, CHAIN_ANSWER_STEP,
                chain_cfg)
    except (Exception, SystemExit) as err:  # noqa: BLE001 — bench must
        # still report (vocab_word_pieces raises SystemExit, which
        # `except Exception` would let escape past the fallback)
        print(f"# production-chain path unavailable ({err!r}); falling "
              "back to random weights + FakeTokenizer (stop OFF)",
              file=sys.stderr)
        return None, None, None, None, cfg


def _sweep_path(params, cfg, on_accel: bool, tokenizer=None,
                expect_conf=None, batches=None):
    """Measure `run_perturbation_sweep` end-to-end: grid build, manifest,
    shared-prefix fused scoring, top-20 logprob maps, D6 + manifest writes.
    A warmup sweep (one full bucket, separate results dir) absorbs the two
    jit compiles; the timed sweep runs all-warm, matching steady state
    where one compile serves ~20k grid cells.

    With ``tokenizer`` (the production-chain path) the engine scores
    through real per-token strings, the digit early stop arms, and every
    row's parsed confidence is asserted equal to ``expect_conf``; without
    it, FakeTokenizer content-free scoring (stop silently off)."""
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep

    if batches is None:
        batches = SWEEP_BATCHES_TPU if on_accel else SWEEP_BATCHES_CPU
        cells = SWEEP_CELLS_TPU if on_accel else SWEEP_CELLS_CPU
    else:
        # --sweep-batches comparisons mix batch ladders (cross-arch
        # tables); an lcm-friendly grid (240 = lcm of 48/40/24/16/8)
        # makes different batch sizes time IDENTICAL grid sizes, so
        # fixed per-run costs amortize the same way in every column
        # (ADVICE r5, bench.py:455).
        cells = 240 if on_accel else SWEEP_CELLS_CPU
    rng = np.random.default_rng(7)
    if tokenizer is not None:
        from chain7b import (CHAIN_CONFIDENCE_FORMAT, CHAIN_RESPONSE_FORMAT,
                             bucket_sized_words)
        words, n_words = bucket_sized_words(tokenizer, rng)
        response_format = CHAIN_RESPONSE_FORMAT
        confidence_format = CHAIN_CONFIDENCE_FORMAT
    else:
        words = ("coverage policy flood water damage claim insurer premium "
                 "exclusion endorsement peril deductible adjuster settle "
                 "liability clause binding interpret statute meaning").split()
        n_words = 170 if on_accel else 12   # 256-token bucket on the chip
        response_format = "Respond with either ' Yes' or ' No' only ."
        confidence_format = "Give a confidence number from 0 to 100 ."

    def long_text():
        return " ".join(rng.choice(words) for _ in range(n_words)) + " ?"

    lp = (LegalPrompt(
        main=long_text(),
        response_format=response_format,
        target_tokens=("Yes", "No"),
        confidence_format=confidence_format),)

    def run(engine, n_cells, tag):
        perts = ([long_text() for _ in range(n_cells - 1)],)
        with tempfile.TemporaryDirectory() as td:
            t0 = time.perf_counter()
            rows = run_perturbation_sweep(
                engine, f"bench-{tag}", lp, perts,
                Path(td) / "results.xlsx", checkpoint_every=100)
            dt = time.perf_counter() - t0
        assert len(rows) == n_cells, (len(rows), n_cells)
        assert all(np.isfinite(r.token_1_prob) for r in rows)
        if expect_conf is not None:
            bad = [r.confidence_value for r in rows
                   if r.confidence_value != expect_conf]
            assert not bad, f"chain confidences off: {bad[:5]}"
        return dt

    last_oom = None
    for batch in batches:
        def make_engine():
            return ScoringEngine(params, cfg,
                                 tokenizer if tokenizer is not None
                                 else FakeTokenizer(),
                                 RuntimeConfig(batch_size=batch,
                                               max_seq_len=512))

        engine = make_engine()
        # Time an exact multiple of the batch: a ragged tail pads into a
        # DIFFERENT batch shape whose fresh compile would land inside the
        # timed run — a bench artifact (production amortizes one compile
        # over ~20k grid cells), not production cost.
        cells_b = max(1, round(cells / batch)) * batch
        try:
            # Cold start: 2*batch cells so BOTH handoff variants of the
            # bucket executable (scratchless first dispatch + donated
            # followers) compile during warmup, not inside the timed run.
            cold_s = run(engine, 2 * batch, "warmup-cold")
            print(f"# sweep warmup COLD (batch {batch}, incl. compiles): "
                  f"{cold_s:.1f}s; compile plan: "
                  f"{json.dumps(engine.compile_stats.summary())}",
                  file=sys.stderr)
            # Warm start: drop the engine and warm up again with the
            # compile plan's executables already present (the registry's
            # process-wide cache — the state a restarted worker reaches
            # after deserializing the persistent cache instead of
            # recompiling). cold - warm is the compile tax the compile
            # plan turns into a managed, refundable artifact.
            engine = make_engine()
            warm_s = run(engine, 2 * batch, "warmup-warm")
            print(f"# sweep warmup WARM (executables from cache): "
                  f"{warm_s:.1f}s ({100 * (1 - warm_s / cold_s):.0f}% "
                  "below cold)", file=sys.stderr)
            dt = run(engine, cells_b, "timed")
        except Exception as err:  # noqa: BLE001 — OOM falls back, rest raises
            if _is_oom(err):
                last_oom = err
                continue
            raise
        stats = engine.compile_stats
        stats.cold_start_s, stats.warm_start_s = cold_s, warm_s
        print(f"# compile plan (warm engine): "
              f"{json.dumps(stats.summary())}", file=sys.stderr)
        return cells_b / dt, batch, cells_b, stats
    print(f"BENCH ABORT: every sweep batch candidate OOMed; last: {last_oom}",
          file=sys.stderr)
    sys.exit(1)


def _varlen_sweep(params, cfg, on_accel: bool, tokenizer=None,
                  expect_conf=None, batches=None):
    """Variable-length sweep mode: ONE corpus-sampled grid (prompt
    lengths drawn from VARLEN_FRAC_DECILES, the distribution recorded in
    SCALE.md) scored TWICE through `run_perturbation_sweep` — ragged
    scheduler ON (bucket ladder + slot refill + prefix groups) vs the
    legacy single-bucket todo-order baseline — on identical cells, the
    same batch size, and a full warmup each (every bucket shape compiles
    before the timed run, matching steady state).

    Returns the dict embedded under the headline JSON's "varlen" key:
    both rates, the ragged margin, and the scheduler's occupancy /
    padding-waste counters (profiling.OccupancyStats). Per-cell results
    are identical between the two runs (pinned by tests/
    test_scheduler.py); this measures dispatch composition only."""
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep

    if batches is None:
        batches = SWEEP_BATCHES_TPU if on_accel else SWEEP_BATCHES_CPU
    cells = VARLEN_CELLS_TPU if on_accel else VARLEN_CELLS_CPU
    rng = np.random.default_rng(13)
    if tokenizer is not None:
        from chain7b import (CHAIN_CONFIDENCE_FORMAT, CHAIN_RESPONSE_FORMAT,
                             bucket_sized_words)
        words, n_words = bucket_sized_words(tokenizer, rng)
        response_format = CHAIN_RESPONSE_FORMAT
        confidence_format = CHAIN_CONFIDENCE_FORMAT
    else:
        words = ("coverage policy flood water damage claim insurer premium "
                 "exclusion endorsement peril deductible adjuster settle "
                 "liability clause binding interpret statute meaning").split()
        n_words = 170 if on_accel else VARLEN_WORDS_CPU
        response_format = "Respond with either ' Yes' or ' No' only ."
        confidence_format = "Give a confidence number from 0 to 100 ."

    # Inverse-CDF draw over the recorded deciles; the same word counts
    # feed both runs, so the two modes score byte-identical prompts.
    u = rng.random(cells)
    fracs = np.interp(u, np.linspace(0.0, 1.0, len(VARLEN_FRAC_DECILES)),
                      VARLEN_FRAC_DECILES)
    counts = [max(4, int(round(f * n_words))) for f in fracs]

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n)) + " ?"

    texts = [text(n) for n in counts]
    lp = (LegalPrompt(main=texts[0], response_format=response_format,
                      target_tokens=("Yes", "No"),
                      confidence_format=confidence_format),)
    perturbations = (texts[1:],)

    def run(engine, tag):
        with tempfile.TemporaryDirectory() as td:
            t0 = time.perf_counter()
            rows = run_perturbation_sweep(
                engine, f"bench-varlen-{tag}", lp, perturbations,
                Path(td) / "results.xlsx", checkpoint_every=1000)
            dt = time.perf_counter() - t0
        assert len(rows) == cells, (len(rows), cells)
        assert all(np.isfinite(r.token_1_prob) for r in rows)
        if expect_conf is not None:
            bad = [r.confidence_value for r in rows
                   if r.confidence_value != expect_conf]
            assert not bad, f"chain confidences off: {bad[:5]}"
        return dt

    last_oom = None
    for batch in batches:
        engines = {
            ragged: ScoringEngine(
                params, cfg,
                tokenizer if tokenizer is not None else FakeTokenizer(),
                RuntimeConfig(batch_size=batch, max_seq_len=512,
                              ragged_scheduler=ragged))
            for ragged in (True, False)}
        try:
            out = {}
            for ragged, engine in engines.items():
                tag = "ragged" if ragged else "baseline"
                t_warm = run(engine, f"{tag}-warmup")  # every shape compiles
                print(f"# varlen warmup ({tag}, batch {batch}, incl. "
                      f"compiles): {t_warm:.1f}s", file=sys.stderr)
                out[ragged] = cells / run(engine, tag)
        except Exception as err:  # noqa: BLE001 — OOM falls back, rest raises
            if _is_oom(err):
                last_oom = err
                continue
            raise
        stats = engines[True].occupancy
        result = {
            "cells": cells, "batch": batch,
            "ragged_p_s": round(out[True], 3),
            "baseline_p_s": round(out[False], 3),
            "ragged_vs_baseline": round(out[True] / out[False], 3),
            "occupancy_pct": round(stats.occupancy_pct, 2),
            "padding_waste_pct": round(stats.padding_waste_pct, 2),
        }
        if stats.decode_steps_paid:
            result["decode_occupancy_pct"] = round(
                stats.decode_occupancy_pct, 2)
        if stats.grouped_cells:
            result["grouped_cells"] = stats.grouped_cells
        print(f"# varlen sweep (corpus-sampled lengths, {cells} cells, "
              f"batch {batch}): ragged {out[True]:.3f} p/s vs "
              f"single-bucket {out[False]:.3f} p/s "
              f"({100 * (out[True] / out[False] - 1):+.1f}%); "
              f"batch occupancy {result['occupancy_pct']:.1f}%, "
              f"padding waste {result['padding_waste_pct']:.1f}%",
              file=sys.stderr)
        return result
    print(f"# varlen sweep: every batch candidate OOMed; last: {last_oom}",
          file=sys.stderr)
    return None


def _serve_bench(params, cfg, on_accel: bool, tokenizer=None,
                 expect_conf=None, batches=None):
    """Online-serving mode: ONE ragged grid (cell lengths drawn from the
    SCALE.md deciles, VARLEN_FRAC_DECILES) measured two ways —

    1. the offline perturbation sweep (run_perturbation_sweep, ragged
       scheduler, full warmup), giving the planned-grid throughput, then
    2. the serving layer (lir_tpu/serve.ScoringServer) under OPEN-LOOP
       Poisson arrivals at SERVE_ARRIVAL_X x that rate, plus
       SERVE_DUP_FRAC duplicate re-asks submitted late (dedup traffic),
       after a full warmup pass over the same shapes.

    Returns the dict embedded under the headline JSON's "serve" key:
    goodput (completed-within-deadline requests/s), p50/p95/p99 latency,
    shed/expired counts, dedup hit rate, slot occupancy, and
    goodput_vs_offline — the acceptance ratio (continuous batching must
    not serve slower than the offline planner on the same cells; it
    skips the plan+Excel+manifest work and dedups repeats, so >= 1 is
    the healthy reading)."""
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig, ServeConfig
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine import grid as grid_mod
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.serve import ScoringServer, ServeRequest

    if batches is None:
        batches = SWEEP_BATCHES_TPU if on_accel else SWEEP_BATCHES_CPU
        cells = SWEEP_CELLS_TPU if on_accel else SERVE_CELLS_CPU
    else:
        cells = 240 if on_accel else SERVE_CELLS_CPU
    rng = np.random.default_rng(23)
    if tokenizer is not None:
        from chain7b import (CHAIN_CONFIDENCE_FORMAT, CHAIN_RESPONSE_FORMAT,
                             bucket_sized_words)
        words, n_words = bucket_sized_words(tokenizer, rng)
        response_format = CHAIN_RESPONSE_FORMAT
        confidence_format = CHAIN_CONFIDENCE_FORMAT
    else:
        words = ("coverage policy flood water damage claim insurer premium "
                 "exclusion endorsement peril deductible adjuster settle "
                 "liability clause binding interpret statute meaning").split()
        n_words = 170 if on_accel else VARLEN_WORDS_CPU
        response_format = "Respond with either ' Yes' or ' No' only ."
        confidence_format = "Give a confidence number from 0 to 100 ."

    # Ragged lengths from the recorded decile table — the serve workload
    # is the production grid's shape, not the fixed-length headline's.
    u = rng.random(cells)
    fracs = np.interp(u, np.linspace(0.0, 1.0, len(VARLEN_FRAC_DECILES)),
                      VARLEN_FRAC_DECILES)
    counts = [max(4, int(round(f * n_words))) for f in fracs]

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n)) + " ?"

    texts = [text(n) for n in counts]
    lp = (LegalPrompt(main=texts[0], response_format=response_format,
                      target_tokens=("Yes", "No"),
                      confidence_format=confidence_format),)
    perturbations = (texts[1:],)
    grid_cells = grid_mod.build_grid("bench-serve", lp, perturbations)
    assert len(grid_cells) == cells

    last_oom = None
    for batch in batches:
        def make_engine():
            return ScoringEngine(params, cfg,
                                 tokenizer if tokenizer is not None
                                 else FakeTokenizer(),
                                 RuntimeConfig(batch_size=batch,
                                               max_seq_len=512))

        try:
            # --- offline baseline: the planned sweep over this grid.
            engine = make_engine()
            for tag in ("warmup", "timed"):
                with tempfile.TemporaryDirectory() as td:
                    t0 = time.perf_counter()
                    rows = run_perturbation_sweep(
                        engine, f"bench-serve-off-{tag}", lp, perturbations,
                        Path(td) / "results.xlsx", checkpoint_every=1000)
                    dt = time.perf_counter() - t0
                assert len(rows) == cells
            offline_p_s = cells / dt
            print(f"# serve mode: offline sweep baseline {offline_p_s:.3f} "
                  f"p/s ({cells} cells, batch {batch})", file=sys.stderr)

            # --- the serving layer over the identical cells.
            engine_srv = make_engine()
            n_dup = max(1, int(round(cells * SERVE_DUP_FRAC)))
            deadline = max(60.0, 4.0 * cells / offline_p_s)
            rate = SERVE_ARRIVAL_X * offline_p_s
            serve_cfg = ServeConfig(
                queue_depth=cells + n_dup + 8,
                # Throughput-biased linger: one full batch's arrival
                # time. Under open-loop overload the queue backlogs
                # anyway, so the window just lets full batches form
                # (latency classes tune this down in real deployments —
                # DEPLOY.md §1d).
                linger_s=min(2.0, batch / rate),
                classes=(("bench", deadline),), default_class="bench")

            def request(cell, i):
                return ServeRequest(binary_prompt=cell.binary_prompt,
                                    confidence_prompt=cell.confidence_prompt,
                                    klass="bench", request_id=str(i))
            # One arrival schedule, drawn once and replayed for BOTH
            # passes: the warm pass realizes (and compiles) every
            # dispatch shape the schedule forms; the timed pass then
            # measures steady state — the same warmup idiom as the
            # offline sweeps. The duplicate re-asks run as a second
            # phase AFTER the main grid resolves (perturbation-style
            # repeat traffic: the re-asked cells have completed, so the
            # content-addressed cache answers without the device).
            main_gaps = rng.exponential(1.0 / rate, size=cells)
            dup_idx = [int(i) for i in rng.integers(
                0, max(1, cells // 2), size=n_dup)]
            dup_gaps = rng.exponential(1.0 / rate, size=n_dup)

            def one_pass(tag):
                server = ScoringServer(engine_srv, "bench-serve",
                                       serve_cfg).start()
                futures = []
                t0 = None
                for i, gap in enumerate(main_gaps):
                    time.sleep(float(gap))
                    if t0 is None:      # window opens at first submit
                        t0 = time.perf_counter()
                    futures.append(server.submit(
                        request(grid_cells[i], f"{tag}-{i}")))
                out = [f.result(timeout=10 * deadline) for f in futures]
                dup_futures = []
                for j, gap in zip(dup_idx, dup_gaps):
                    time.sleep(float(gap))
                    dup_futures.append(server.submit(
                        request(grid_cells[j], f"{tag}-dup-{j}")))
                out += [f.result(timeout=10 * deadline)
                        for f in dup_futures]
                dt = time.perf_counter() - t0
                server.stop()
                return server, out, dt

            # Warm pass + best-of-3 measured passes (the isolated
            # step's best-of idiom): dispatch composition is
            # arrival-timing-dependent, so a pass can form a shape no
            # earlier pass compiled — the jit caches accumulate across
            # passes and the best pass is the all-warm steady state.
            one_pass("warm")
            server, results, elapsed = min(
                (one_pass(f"timed{k}") for k in range(3)),
                key=lambda t: t[2])
        except Exception as err:  # noqa: BLE001 — OOM falls back
            if _is_oom(err):
                last_oom = err
                continue
            raise
        stats = server.stats
        ok = [r for r in results if r.status == "ok"]
        if expect_conf is not None:
            bad = [r.confidence_value for r in ok
                   if r.confidence_value != expect_conf]
            assert not bad, f"serve chain confidences off: {bad[:5]}"
        goodput = stats.goodput(elapsed)
        out = {
            "cells": cells, "dup_requests": n_dup, "batch": batch,
            "arrival_rps": round(rate, 3),
            "goodput_p_s": round(goodput, 3),
            "offline_p_s": round(offline_p_s, 3),
            "goodput_vs_offline": round(goodput / offline_p_s, 3),
            "completed": stats.completed, "shed": stats.shed,
            "deadline_exceeded": stats.expired, "late": stats.late,
            "dedup_hit_rate": round(stats.dedup_hit_rate, 4),
            "slot_occupancy_pct": round(stats.slot_occupancy_pct, 2),
            "promoted": stats.promoted,
        }
        out.update(stats.latency_percentiles())
        print(f"# serve mode ({cells + n_dup} reqs at {rate:.2f} rps "
              f"open-loop): goodput {goodput:.3f} p/s "
              f"({out['goodput_vs_offline']:.2f}x offline), p50/p95/p99 "
              f"{out['p50_s']:.3f}/{out['p95_s']:.3f}/{out['p99_s']:.3f}s, "
              f"dedup {100 * stats.dedup_hit_rate:.0f}%, shed {stats.shed}",
              file=sys.stderr)
        return out
    print(f"# serve mode: every batch candidate OOMed; last: {last_oom}",
          file=sys.stderr)
    return None


def _prefix_serve_bench(params, cfg, on_accel: bool, tokenizer=None,
                        share: float = 0.8, batches=None):
    """Prefix-heavy serve mode (PREFIX_BASES comment above): the same
    open-loop Poisson trace — ``share`` of arrivals are variations of
    one of 5 long legal-prompt bases — served twice on separate engines:

    1. prefix cache OFF (ServeConfig(prefix_cache=False)) — the PR-3
       baseline, where only exact-match dedup could help and none of
       these requests are exact matches;
    2. prefix cache ON — warm dispatches resume each row's shared base
       from the radix page pool and prefill only the variation suffix.

    Both servers see the IDENTICAL arrival gaps and request contents;
    per-request payloads must match bitwise (parity_ok) — the prefix
    cache is a pure perf lever. Returns the "prefix_serve" headline
    dict, or None when every batch candidate OOMs."""
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig, ServeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.serve import ScoringServer, ServeRequest

    if batches is None:
        batches = SWEEP_BATCHES_TPU if on_accel else SWEEP_BATCHES_CPU
    cells = PREFIX_CELLS_TPU if on_accel else PREFIX_CELLS_CPU
    rng = np.random.default_rng(29)
    if tokenizer is not None:
        from chain7b import (CHAIN_CONFIDENCE_FORMAT, CHAIN_RESPONSE_FORMAT,
                             bucket_sized_words)
        words, n_words = bucket_sized_words(tokenizer, rng)
        response_format = CHAIN_RESPONSE_FORMAT
        confidence_format = CHAIN_CONFIDENCE_FORMAT
    else:
        words = ("coverage policy flood water damage claim insurer premium "
                 "exclusion endorsement peril deductible adjuster settle "
                 "liability clause binding interpret statute meaning").split()
        # LONG bases on CPU too (unlike the generic serve smoke): the
        # whole point of this mode is the production shape — legal
        # prompts hundreds of tokens long, variations a few tokens —
        # where prefill dominates and the radix cache refunds it.
        n_words = 170
        response_format = "Respond with either ' Yes' or ' No' only ."
        confidence_format = "Give a confidence number from 0 to 100 ."

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n))

    bases = [text(n_words) for _ in range(PREFIX_BASES)]
    reqs = []
    n_shared = 0
    for i in range(cells):
        if rng.random() < share:
            n_shared += 1
            main = f"{bases[i % PREFIX_BASES]} case {i} ?"
        else:
            main = f"{text(n_words)} case {i} ?"
        reqs.append((f"{main} {response_format}",
                     f"{main} {confidence_format}"))

    last_oom = None
    for batch in batches:
        def make_engine():
            return ScoringEngine(params, cfg,
                                 tokenizer if tokenizer is not None
                                 else FakeTokenizer(),
                                 RuntimeConfig(
                                     batch_size=batch, max_seq_len=512,
                                     prefix_cache_pages=PREFIX_POOL_PAGES))

        try:
            engines = {"baseline": make_engine(), "prefix": make_engine()}
            cfgs = {
                "baseline": ServeConfig(queue_depth=cells + 8,
                                        prefix_cache=False,
                                        classes=(("bench", 600.0),),
                                        default_class="bench"),
                "prefix": ServeConfig(queue_depth=cells + 8,
                                      prefix_cache=True,
                                      classes=(("bench", 600.0),),
                                      default_class="bench"),
            }

            def one_pass(kind, gaps):
                server = ScoringServer(engines[kind], f"bench-prefix-{kind}",
                                       cfgs[kind]).start()
                futures = []
                t0 = None
                for (bp, cp), gap in zip(reqs, gaps):
                    time.sleep(float(gap))
                    if t0 is None:
                        t0 = time.perf_counter()
                    futures.append(server.submit(ServeRequest(
                        binary_prompt=bp, confidence_prompt=cp,
                        klass="bench", request_id=str(len(futures)))))
                out = [f.result(timeout=600) for f in futures]
                dt = time.perf_counter() - t0
                server.stop()
                return server, out, dt

            zero_gaps = [0.0] * cells
            # Warm passes (two per server, the serve-mode idiom):
            # compile every dispatch shape — the prefix engine's first
            # pass is its COLD pass (unpaged dispatches + page inserts),
            # its second realizes the warm paged window shapes — then
            # size the open-loop arrival rate off the BASELINE's second
            # warm pass.
            one_pass("baseline", zero_gaps)
            one_pass("prefix", zero_gaps)
            one_pass("prefix", zero_gaps)
            _, _, base_dt = one_pass("baseline", zero_gaps)
            rate = SERVE_ARRIVAL_X * cells / base_dt
            gaps = rng.exponential(1.0 / rate, size=cells)
            pfx_stats0 = engines["prefix"].prefix_stats.summary()
            # Best-of-2 timed passes per server on the IDENTICAL trace
            # (dispatch composition is arrival-timing-dependent; jit
            # caches accumulate across passes, and the best pass is the
            # all-warm steady state).
            base_srv, base_out, base_elapsed = min(
                (one_pass("baseline", gaps) for _ in range(2)),
                key=lambda t: t[2])
            pfx_srv, pfx_out, pfx_elapsed = min(
                (one_pass("prefix", gaps) for _ in range(2)),
                key=lambda t: t[2])
        except Exception as err:  # noqa: BLE001 — OOM falls back
            if _is_oom(err):
                last_oom = err
                continue
            raise
        # Per-request parity: the prefix cache must be invisible in the
        # payloads — every measurement field identical (float-exact) to
        # the PR-3 baseline on the same trace.
        fields = ("status", "token_1_prob", "token_2_prob",
                  "log_probabilities", "confidence_value",
                  "weighted_confidence", "model_response",
                  "model_confidence_response")
        mismatches = sum(
            1 for a, b in zip(base_out, pfx_out)
            if any(getattr(a, f, None) != getattr(b, f, None)
                   for f in fields))
        pfx_stats1 = engines["prefix"].prefix_stats.summary()
        avoided = (pfx_stats1["prefill_tokens_avoided"]
                   - pfx_stats0["prefill_tokens_avoided"])
        total = (pfx_stats1["prefill_tokens_total"]
                 - pfx_stats0["prefill_tokens_total"])
        base_goodput = base_srv.stats.goodput(base_elapsed)
        pfx_goodput = pfx_srv.stats.goodput(pfx_elapsed)
        out = {
            "requests": cells, "shared": n_shared, "batch": batch,
            "share": round(n_shared / cells, 3),
            "arrival_rps": round(rate, 3),
            "goodput_p_s": round(pfx_goodput, 3),
            "baseline_p_s": round(base_goodput, 3),
            "goodput_vs_baseline": round(
                pfx_goodput / base_goodput, 3) if base_goodput else 0.0,
            "prefill_tokens_avoided": int(avoided),
            "prefill_tokens_total": int(total),
            "avoided_frac": round(avoided / total, 4) if total else 0.0,
            "radix_hit_rate": pfx_stats1["radix_hit_rate"],
            "inserted_pages": pfx_stats1["inserted_pages"],
            "evicted_pages": pfx_stats1["evicted_pages"],
            "pages_in_use": pfx_stats1["pages_in_use"],
            "parity_ok": mismatches == 0,
            "parity_mismatches": mismatches,
        }
        print(f"# prefix serve mode ({cells} reqs, {n_shared} sharing "
              f"{PREFIX_BASES} bases, {rate:.2f} rps open-loop): goodput "
              f"{pfx_goodput:.3f} p/s ({out['goodput_vs_baseline']:.2f}x "
              f"the exact-dedup baseline), prefill tokens avoided "
              f"{avoided}/{total} ({100 * out['avoided_frac']:.0f}%), "
              f"parity {'OK' if mismatches == 0 else 'FAIL'}",
              file=sys.stderr)
        return out
    print(f"# prefix serve mode: every batch candidate OOMed; "
          f"last: {last_oom}", file=sys.stderr)
    return None


def _fleet_bench(on_accel: bool):
    """Multi-model fleet mode: the inter-model agreement workload
    (paper axis 2 — every question scored under ALL N models, κ over
    the decisions) arriving as question WAVES, measured two ways on the
    identical waves:

    1. sequential drop-and-reload (the pre-fleet reality: one model
       resident at a time, every switch re-converts + re-uploads the
       next model's weights serially before its first dispatch);
    2. the fleet scheduler (engine/fleet.py): all models co-resident up
       to the weight-cache budget (revisits are cache hits), misses
       streamed by the async prefetcher BEHIND the previous model's
       compute.

    Per-model scores are asserted BITWISE identical across the two
    paths before reporting (weights are moved, never transformed), and
    the within-question kappa over the fleet's decisions is computed
    through the stats/streaming contingency path — the number the
    agreement axis exists to produce. Models share one ModelConfig
    (distinct weights per model id) so both paths reuse one set of
    executables: the measured delta is pure weight logistics, never
    compile skew."""
    import time as _time

    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.fleet import ModelFleet
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import loader
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.stats import streaming

    n_models, n_waves, q_per_wave = 6, 4, 2
    # Sized so one model's checkpoint-load (torch-layout convert +
    # host->device upload, the REAL loader path) is comparable to one
    # wave of <=10-token scoring — the ServerlessLLM regime the fleet
    # targets. bf16 + a deeper stack on accelerators.
    if on_accel:
        D, L, F = 2048, 4, 4096
        dtype = jnp.bfloat16
    else:
        D, L, F = 512, 3, 1024
        dtype = jnp.float32
    V = FakeTokenizer.VOCAB
    cfg = ModelConfig(name="fleet-member", vocab_size=V, hidden_size=D,
                      n_layers=L, n_heads=8, intermediate_size=F,
                      max_seq_len=256, tie_embeddings=True)
    rt = RuntimeConfig(batch_size=4, max_seq_len=256, max_new_tokens=6)

    def host_sd(seed: int):
        """Torch-layout llama state dict in host RAM — the checkpoint
        stand-in both paths load through loader.convert_decoder."""
        rng = np.random.default_rng(seed)
        sd = {"embed_tokens.weight":
              rng.standard_normal((V, D)).astype(np.float32) * 0.02,
              "norm.weight": np.ones(D, np.float32)}
        for i in range(L):
            p = f"layers.{i}."
            sd[p + "input_layernorm.weight"] = np.ones(D, np.float32)
            sd[p + "post_attention_layernorm.weight"] = np.ones(
                D, np.float32)
            for k, shape in (("self_attn.q_proj", (D, D)),
                             ("self_attn.k_proj", (D, D)),
                             ("self_attn.v_proj", (D, D)),
                             ("self_attn.o_proj", (D, D)),
                             ("mlp.gate_proj", (F, D)),
                             ("mlp.up_proj", (F, D)),
                             ("mlp.down_proj", (D, F))):
                sd[p + k + ".weight"] = (
                    rng.standard_normal(shape).astype(np.float32) * 0.02)
        return sd

    sds = {f"fleet-m{i}": host_sd(i) for i in range(n_models)}

    def factory(name: str) -> ScoringEngine:
        params = loader.convert_decoder(sds[name], cfg, "llama",
                                        dtype=dtype)
        jax.block_until_ready(jax.tree.leaves(params)[0])
        return ScoringEngine(params, cfg, FakeTokenizer(), rt)

    rng = np.random.default_rng(11)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement").split()
    waves = [[" ".join(rng.choice(words) for _ in range(10)) + " ?"
              for _ in range(q_per_wave)] for _ in range(n_waves)]
    mids = list(sds)

    def score(engine, qs):
        return [(r.yes_prob, r.no_prob) for r in engine.score_prompts(qs)]

    # Warm every executable once so neither timed path pays a compile
    # (shared cfg => shared jit cache across models and paths).
    score(factory(mids[0]), waves[0])

    t0 = _time.perf_counter()
    seq = {m: [] for m in mids}
    for wave in waves:
        for mid in mids:
            engine = factory(mid)       # reload-per-switch, serial
            seq[mid].extend(score(engine, wave))
            engine = None               # drop: one model resident
    sequential_s = _time.perf_counter() - t0

    fleet = ModelFleet.from_factory(factory, mids, stage_reloads=False)
    t0 = _time.perf_counter()
    fl = {m: [] for m in mids}
    for wave in waves:
        out = fleet.sweep(mids, lambda mid, eng: score(eng, wave))
        for m in mids:
            fl[m].extend(out[m])
    fleet_s = _time.perf_counter() - t0
    fleet.shutdown()

    parity_ok = fl == seq               # exact float equality, per score
    assert parity_ok, "fleet scores diverged from single-model engines"
    s = fleet.stats.summary()
    assert s["swap_s_hidden"] > s["swap_s_exposed"], (
        "prefetch failed to hide swaps behind compute", s)
    # Within-question kappa across the fleet — the agreement number,
    # through the exact streaming contingency path.
    groups, decisions = [], []
    for m in mids:
        for q, (yes, no) in enumerate(fl[m]):
            groups.append(q)
            decisions.append(1 if yes > no else 0)
    kap = streaming.kappa_from_counts(*streaming.group_counts(
        np.asarray(groups), np.asarray(decisions)))
    rows = n_models * n_waves * q_per_wave
    return {
        "n_models": n_models,
        "waves": n_waves,
        "questions_per_wave": q_per_wave,
        "sequential_s": round(sequential_s, 3),
        "fleet_s": round(fleet_s, 3),
        "fleet_vs_sequential": round(sequential_s / fleet_s, 3),
        "fleet_p_s": round(rows / fleet_s, 3),
        "sequential_p_s": round(rows / sequential_s, 3),
        "swap_s_hidden": s["swap_s_hidden"],
        "swap_s_exposed": s["swap_s_exposed"],
        "swap_hidden_frac": s["swap_hidden_frac"],
        "prefetch_hits": s["prefetch_hits"],
        "cache_hits": s["cache_hits"],
        "loads": s["loads"],
        "evictions": s["evictions"],
        "parity_ok": parity_ok,
        "kappa": {k: round(float(v), 6) for k, v in kap.items()},
    }


def _observatory_bench(on_accel: bool):
    """Reliability-observatory mode (ROADMAP item 5): the first mode to
    exercise fleet_score traffic + scheduled sentinel sweeps +
    stats/metrics polling UNDER ONE SERVER at once.

    Two runs over identical client waves (fresh servers, same weights,
    shared executables so the delta is pure observability):

    1. OFF baseline: fleet server, client fleet_score waves only, no
       recorder/registry polling/scheduler.
    2. ON: trace recorder installed, SentinelScheduler sweeping a
       sentinel grid into 3 drift windows (driven by a synthetic
       scheduler clock so window boundaries are deterministic), the
       stats/metrics endpoints polled every wave, and a seeded
       fault-plan NaN injection on one model during window 3.

    Asserted before reporting: exactly ONE drift alert naming window 3
    and the injected model (caught within one window), zero
    clean-window false alarms, per-window kappa BITWISE equal to
    within_group_kappa recomputed from the sweep payloads (an
    independent path: host payload decisions vs the device lattice),
    and CLIENT goodput at least 0.95x the OFF baseline — the gate is
    the metrics/tracing bookkeeping (spans, registry snapshots,
    windowed folding) staying off the dispatch hot path, measured on
    identical client work (median per-wave time, so one scheduler
    hiccup can't fake a regression); the sentinel sweeps' own device
    time is DELIBERATE added work and is reported separately
    (sentinel_sweep_s), not smuggled into the overhead ratio."""
    import time as _time

    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import ObserveConfig, RuntimeConfig, ServeConfig
    from lir_tpu.engine.fleet import ModelFleet
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.faults.plan import FaultPlan, SiteSchedule
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.observe import SentinelScheduler, tracing
    from lir_tpu.serve import (FleetScoringServer, ServeRequest,
                               fleet_decision)
    from lir_tpu.stats.kappa import within_group_kappa

    n_models, n_waves, q_per_wave = 3, 9, 4
    window_s = 100.0
    names = [f"obs-m{i}" for i in range(n_models)]

    def _cfg(name):
        return ModelConfig(name=name, vocab_size=FakeTokenizer.VOCAB,
                           hidden_size=64 if on_accel else 32,
                           n_layers=1, n_heads=2, intermediate_size=64,
                           max_seq_len=256)

    def _server():
        fleet = ModelFleet.from_engines(
            [(n, ScoringEngine(
                decoder.init_params(_cfg(n), jax.random.PRNGKey(i)),
                _cfg(n), FakeTokenizer(),
                RuntimeConfig(batch_size=4, max_seq_len=256)))
             for i, n in enumerate(names)])
        return fleet, FleetScoringServer(
            fleet, ServeConfig(linger_s=0.002)).start()

    rng = np.random.default_rng(5)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement").split()
    waves = [[" ".join(rng.choice(words) for _ in range(10)) + " ?"
              for _ in range(q_per_wave)] for _ in range(n_waves)]

    def _req(q, rid):
        return ServeRequest(
            binary_prompt=f"{q} Answer Yes or No.",
            confidence_prompt=f"{q} Give a confidence 0-100.",
            request_id=rid)

    def _run_waves(server, per_wave=None):
        """Drive the client waves; returns per-wave client seconds
        (submit -> all resolved). ``per_wave`` (scheduler ticks,
        endpoint polls) runs BETWEEN waves, outside the client slice —
        its cost is reported on its own."""
        wave_s = []
        for w, wave in enumerate(waves):
            t0 = _time.perf_counter()
            futs = [server.submit_fleet(_req(q, f"w{w}q{j}"))
                    for j, q in enumerate(wave)]
            for f in futs:
                f.result(60.0)
            wave_s.append(_time.perf_counter() - t0)
            if per_wave is not None:
                per_wave(w)
        return wave_s

    # Four sentinels = one full-batch dispatch per model per sweep, so
    # sentinel traffic rides the same executable shape as client waves.
    sentinels = [_req(q, f"sent{j}")
                 for j, q in enumerate(["Is a cat an animal",
                                        "Is rain considered weather",
                                        "Is a rock an animal",
                                        "Is a contract binding"])]

    # Warmup: compiles the shared scoring executables AND the
    # observatory's own programs (windowed fold_update, the drift
    # window reduce) so neither timed run pays a trace — the measured
    # delta is steady-state bookkeeping, not one-off compiles.
    fleet, server = _server()
    _run_waves(server)
    warm_now = {"t": window_s}
    warm_sched = SentinelScheduler(
        server, sentinels,
        cfg=ObserveConfig(sentinel_interval_s=0.0,
                          sentinel_window_s=window_s),
        clock=lambda: warm_now["t"])
    warm_sched.tick()
    warm_sched.finalize_all()
    server.stop()
    fleet.shutdown()

    client_reqs = n_waves * q_per_wave * n_models

    # 1. Observability OFF.
    fleet, server = _server()
    off_wave_s = _run_waves(server)
    off_completed = server.stats.completed
    server.stop()
    fleet.shutdown()
    goodput_off = client_reqs / sum(off_wave_s)

    # 2. Observability ON: tracing + scheduler + endpoint polling.
    rec = tracing.TraceRecorder()
    prev = tracing.set_recorder(rec)
    try:
        fleet, server = _server()
        sched_now = {"t": window_s}
        # Interval 2.5 "seconds" against the +1-per-wave synthetic
        # clock = one sentinel sweep per 3-wave window — the production
        # duty cycle (sweeps are sparse against client traffic), and
        # the remaining waves exercise the tick-not-due path.
        sched = SentinelScheduler(
            server, sentinels,
            cfg=ObserveConfig(sentinel_interval_s=2.5,
                              sentinel_window_s=window_s,
                              drift_min_windows=2),
            clock=lambda: sched_now["t"])
        server.attach_observatory(sched)
        plan = FaultPlan(seed=9, schedules={
            "dispatch": SiteSchedule(rate=1.0, kind="nan",
                                     nan_rows=(0, 1, 2, 3))})
        victim = server.batcher.batchers[names[0]]
        orig_score = victim.score
        armed = {"v": False}
        sweep_decisions = {}        # window -> payload-level decisions
        sweep_s = [0.0]

        def per_wave(w):
            # Windows 1/2/3 over thirds of the wave stream; injection
            # armed for window 3's sweep; endpoint polling every wave.
            window = 1 + w // (n_waves // 3)
            sched_now["t"] = window * window_s + (w % 3) + 1.0
            if window == 3 and not armed["v"]:
                armed["v"] = True
                victim.score = plan.wrap("dispatch", victim.score)
            t0 = _time.perf_counter()
            rec_sweep = sched.tick()
            sweep_s[0] += _time.perf_counter() - t0
            if rec_sweep is not None:
                groups, decs = sweep_decisions.setdefault(
                    rec_sweep["window"], ([], []))
                for j, per_model in enumerate(rec_sweep["results"]):
                    for mid, row in per_model.items():
                        d = (fleet_decision(row.get("token_1_prob"),
                                            row.get("token_2_prob"))
                             if row.get("status") == "ok" else None)
                        if d is not None:
                            groups.append(
                                (rec_sweep["slot"], j))
                            decs.append(d)
            # Endpoint polling rides the same mixed load.
            server.stats_summary()
            server.metrics.snapshot(device_memory=False)

        on_wave_s = _run_waves(server, per_wave)
        on_completed = server.stats.completed
        victim.score = orig_score
        sched_now["t"] = 4 * window_s + 1.0
        sched.finalize_closed()
        obs = sched.summary()
        snap = server.metrics.snapshot()
        trace_doc = rec.export_chrome()
        server.stop()
        fleet.shutdown()
    finally:
        tracing.set_recorder(prev)
    goodput_on = client_reqs / sum(on_wave_s)

    # -- the acceptance gates -------------------------------------------------
    alerts = obs["alerts"]
    assert len(alerts) == 1, f"expected exactly 1 drift alert: {alerts}"
    assert alerts[0]["window"] == 3, alerts[0]
    assert any(m.get("model") == names[0]
               for m in alerts[0]["metrics"]), alerts[0]
    clean_false_alarms = sum(1 for w in obs["windows"]
                             if w["window"] != 3 and w.get("drifted"))
    assert clean_false_alarms == 0, obs["windows"]
    # Per-window kappa: lattice path (device reduce -> kappa_from_
    # counts) bitwise vs within_group_kappa over the PAYLOAD decisions
    # the bench recorded itself.
    kappa_bitwise = True
    for w in obs["windows"]:
        groups, decs = sweep_decisions.get(w["window"], ([], []))
        uniq = {g: i for i, g in enumerate(sorted(set(groups)))}
        ref = within_group_kappa(
            np.asarray(decs, int),
            np.asarray([uniq[g] for g in groups], int))
        same = (w["kappa"]["kappa"] == ref["kappa"]
                or (np.isnan(w["kappa"]["kappa"])
                    and np.isnan(ref["kappa"])))
        kappa_bitwise = kappa_bitwise and same
    assert kappa_bitwise, "window kappa diverged from payload kappa"
    # Overhead gate on MEDIAN per-wave client time (identical work both
    # runs; the median makes one noisy wave unable to fake a
    # regression). The mean-based goodputs are reported alongside.
    med_off = float(np.median(off_wave_s))
    med_on = float(np.median(on_wave_s))
    goodput_ratio = med_off / med_on
    assert goodput_ratio >= 0.95, (
        f"observability overhead too high: client goodput "
        f"{goodput_ratio:.3f}x the off baseline")
    n_spans = len(trace_doc["traceEvents"])
    span_names = {e["name"] for e in trace_doc["traceEvents"]
                  if e.get("ph") == "X"}
    for must in ("serve/admit", "serve/queue_wait", "serve/dispatch",
                 "serve/readout", "serve/resolve", "sentinel/sweep"):
        assert must in span_names, f"missing span {must}"

    return {
        "n_models": n_models,
        "waves": n_waves,
        "questions_per_wave": q_per_wave,
        "n_sentinels": len(sentinels),
        "windows": len(obs["windows"]),
        "sentinel_sweeps": obs["sweeps"],
        "alerts": len(alerts),
        "drift_window": alerts[0]["window"],
        "drift_detected_within_one_window": True,
        "clean_window_false_alarms": clean_false_alarms,
        "kappa_bitwise_vs_within_group_kappa": kappa_bitwise,
        "per_window_kappa": {
            str(w["window"]): round(float(w["kappa"]["kappa"]), 6)
            for w in obs["windows"]},
        "client_goodput_off_p_s": round(goodput_off, 3),
        "client_goodput_on_p_s": round(goodput_on, 3),
        "goodput_ratio": round(goodput_ratio, 3),
        "sentinel_sweep_s": round(sweep_s[0], 4),
        "completed_on": int(on_completed),
        "completed_off": int(off_completed),
        "trace_spans": n_spans,
        "metrics_sources": len(snap["sources"]),
    }


def _spec_bench(on_accel: bool):
    """Speculative-decode mode (ROADMAP item 3): the identical
    confidence-tail grid swept twice on a speculation-ON engine (pass 2
    drafts every row's continuation from the radix tree's token
    history, recorded during pass 1) and twice on a speculation-OFF
    engine. Gates asserted before reporting:

    - PARITY: every per-cell result (the full value-column row —
      probabilities, confidence, top-20 map, response text) is
      bitwise-identical between ON and OFF, on both the cold and the
      warm pass — speculation is a pure perf lever;
    - the warm pass runs >= 2x FEWER decode dispatches per row than
      the sequential scan (SpecStats decode_forwards vs seq_forwards
      — the verify window replaces spec_k sequential steps when drafts
      land);
    - CPU interpret-mode parity: the SAME comparison with the Pallas
      multi-query verify kernel engaged under the interpreter
      (flash_decode_mq — the kernel that runs compiled on the chip),
      so the fused verify route is covered off-TPU too.
    """
    import tempfile

    import jax
    import numpy as np
    import pandas as pd

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data import schemas
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models import decoder as decoder_mod
    from lir_tpu.models.registry import ModelConfig

    cfg = ModelConfig(name="spec-bench", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=2, n_heads=4, n_kv_heads=2,
                      intermediate_size=64, max_seq_len=512)
    params = decoder_mod.init_params(cfg, jax.random.PRNGKey(37))
    rng = np.random.default_rng(41)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster").split()

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n))

    lp = (LegalPrompt(main=text(40) + " ?",
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    perts = ([text(40) for _ in range(11)],)

    def engine(spec_on):
        return ScoringEngine(params, cfg, FakeTokenizer(), RuntimeConfig(
            batch_size=4, max_seq_len=512, spec_decode=spec_on, spec_k=4,
            piggyback_prefill=False, prefix_cache=True,
            prefix_cache_pages=256))

    value_cols = ["Token_1_Prob", "Token_2_Prob", "Confidence Value",
                  "Weighted Confidence", "Log Probabilities",
                  "Model Response", "Model Confidence Response"]

    def rows_by_key(path):
        df = schemas.read_results_frame(path)
        return {
            (r["Rephrased Main Part"], r["Response Format"]): tuple(
                r[c] for c in value_cols)
            for _, r in df.iterrows()}

    def sweep_twice(spec_on, td):
        eng = engine(spec_on)
        run_perturbation_sweep(eng, "spec-bench", lp, perts,
                               td / f"{spec_on}-cold.csv",
                               checkpoint_every=6)
        eng.spec_flush()
        cold_fwd = eng.spec_stats.decode_forwards
        cold_seq = eng.spec_stats.seq_forwards
        run_perturbation_sweep(eng, "spec-bench", lp, perts,
                               td / f"{spec_on}-warm.csv",
                               checkpoint_every=6)
        eng.spec_flush()
        return eng, cold_fwd, cold_seq

    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        eng_on, cold_fwd, cold_seq = sweep_twice(True, td)
        eng_off, _, _ = sweep_twice(False, td)
        parity_ok = True
        for leg in ("cold", "warm"):
            on = rows_by_key(td / f"True-{leg}.csv")
            off = rows_by_key(td / f"False-{leg}.csv")
            for k, want in off.items():
                got = on.get(k)
                if got is None:
                    parity_ok = False
                    continue
                for g, w in zip(got, want):
                    if pd.isna(g) and pd.isna(w):
                        continue
                    if g != w:
                        parity_ok = False
        assert parity_ok, "speculative ON vs OFF per-cell results diverged"

        s = eng_on.spec_stats
        warm_fwd = s.decode_forwards - cold_fwd
        warm_seq = s.seq_forwards - cold_seq
        ratio = warm_seq / max(warm_fwd, 1)
        assert s.accepted_tokens > 0, "no draft was ever accepted"
        assert ratio >= 2.0, (
            f"warm pass ran only {ratio:.2f}x fewer decode dispatches")

    # Interpret-mode leg: the Pallas multi-query verify kernel under the
    # interpreter (the compiled-kernel route, off-chip) — consumed
    # readouts must still match the sequential fused path exactly.
    interp_ok = True
    if not on_accel:
        prev = decoder_mod.FUSED_DECODE_INTERPRET_ON_CPU
        decoder_mod.FUSED_DECODE_INTERPRET_ON_CPU = True
        try:
            fcfg = ModelConfig(name="spec-bench-interp",
                               vocab_size=FakeTokenizer.VOCAB,
                               hidden_size=32, n_layers=1, n_heads=2,
                               intermediate_size=64, max_seq_len=256,
                               fused_decode=True)
            fparams = decoder_mod.init_params(fcfg, jax.random.PRNGKey(5))
            tokz = FakeTokenizer()
            bp = [text(20) + " yes or no" for _ in range(3)]
            cp = [p + " give confidence" for p in bp]

            def one(spec_on):
                eng = ScoringEngine(fparams, fcfg, tokz, RuntimeConfig(
                    batch_size=4, max_seq_len=256, spec_decode=spec_on,
                    spec_k=3, piggyback_prefill=False, fused_decode=True))
                yes = np.full((3,), eng.yes_id, np.int32)
                no = np.full((3,), eng.no_id, np.int32)
                return jax.device_get(eng.decode_fused_shared(
                    bp, cp, yes, no, new_tokens=3, conf_tokens=4,
                    reuse_cache=True))

            a_on, c_on = one(True)
            a_off, c_off = one(False)
            for on_o, off_o in ((a_on, a_off), (c_on, c_off)):
                interp_ok &= np.array_equal(np.asarray(on_o.generated),
                                            np.asarray(off_o.generated))
                interp_ok &= np.array_equal(
                    np.asarray(on_o.p_yes)[:, 0],
                    np.asarray(off_o.p_yes)[:, 0])
                interp_ok &= np.array_equal(
                    np.asarray(on_o.topk_logprobs),
                    np.asarray(off_o.topk_logprobs))
            assert interp_ok, "interpret-mode speculative parity failed"
        finally:
            decoder_mod.FUSED_DECODE_INTERPRET_ON_CPU = prev

    return {
        "dispatches_per_row_ratio": round(ratio, 2),
        "warm_decode_forwards": int(warm_fwd),
        "warm_seq_forwards": int(warm_seq),
        "accept_rate": round(s.accept_rate, 4),
        "accepted_tokens": int(s.accepted_tokens),
        "rejected_tokens": int(s.rejected_tokens),
        "draft_source": s.summary()["draft_source"],
        "parity_ok": bool(parity_ok),
        "interp_parity_ok": bool(interp_ok),
    }


def _cascade_bench(on_accel: bool):
    """Cascade-prefill mode (ROADMAP item 1): the sweep grid reshaped to
    the paper's axis-1 worst case — every rephrasing shares one long
    legal trunk verbatim — swept twice (cold + radix-warm) on a
    cascade-ON engine and twice on a cascade-OFF engine. Gates asserted
    before reporting:

    - PARITY at the PR-7 bar: per-cell argmax-derived columns (response
      texts, parsed confidence) IDENTICAL between ON and OFF on both
      passes; float columns within FLOAT_TOL (the cascade reorders the
      log-sum-exp reduction, so interior floats are tolerance-bound —
      the same bar tests/test_cascade.py pins);
    - the cascade engaged: nonzero cascade dispatches and analytic
      prefix FLOPs saved (CascadeStats), and the OFF engine never took
      the cascade path;
    - the PLATEAU gate: the grid's useful prefill FLOPs with the trunk
      deduped vs paid densely imply a prefill-phase MFU and an
      isolated-step p/s ABOVE the 36% / ~41 p/s plateau pinned since
      BENCH_r02 — the `kernels` key's prefill phase finally moving. Off
      the chip the projection is analytic (useful-FLOPs ratio times the
      recorded r05 plateau; wall-clock MFU means nothing on CPU, where
      the kernel runs under the Pallas interpreter); on TPU the same
      ratio rides the measured step.
    """
    import ast
    import tempfile

    import jax
    import numpy as np
    import pandas as pd

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data import schemas
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models import decoder as decoder_mod
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.utils import profiling

    PLATEAU_MFU = 36.0   # % — BENCH_r02–r05 isolated-step MFU plateau
    PLATEAU_PS = 41.0    # p/s — the isolated scoring step the plateau pins
    FLOAT_TOL = 1e-4

    cfg = ModelConfig(name="cascade-bench", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=2, n_heads=4, n_kv_heads=2,
                      intermediate_size=64, max_seq_len=512)
    params = decoder_mod.init_params(cfg, jax.random.PRNGKey(43))
    rng = np.random.default_rng(47)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster").split()

    def text(n):
        return " ".join(rng.choice(words) for _ in range(n))

    # Long shared trunks, 3 cells each — the few-rephrasings-per-base
    # regime: per-trunk runs sit BELOW the scheduler's cross-cell
    # grouping floor (min_group_cells=4, which would dedup the trunk by
    # sharing ONE prefill outright) but above the cascade's min_rows=2,
    # so the shared-trunk dedup can only come from the cascade — the
    # coverage the cascade adds beyond PR-9 grouping. batch_size=3
    # aligns each shared dispatch with exactly one trunk's cells.
    trunks = [text(48) for _ in range(4)]
    bin_fmt = "Answer Yes or No ."
    conf_fmt = "Give a number from 0 to 100 ."
    lp = (LegalPrompt(main=f"{trunks[0]} original claim ?",
                      response_format=bin_fmt,
                      target_tokens=("Yes", "No"),
                      confidence_format=conf_fmt),)
    perts = ([f"{trunks[0]} {text(3)} ?" for _ in range(2)]
             + [f"{t} {text(3)} ?" for t in trunks[1:] for _ in range(3)],)

    def engine(cascade_on):
        return ScoringEngine(params, cfg, FakeTokenizer(), RuntimeConfig(
            batch_size=3, max_seq_len=512, piggyback_prefill=False,
            prefix_cache=True, prefix_cache_pages=256,
            cascade_prefill=cascade_on))

    exact_cols = ["Confidence Value", "Model Response",
                  "Model Confidence Response"]
    float_cols = ["Token_1_Prob", "Token_2_Prob", "Weighted Confidence"]

    def rows_by_key(path):
        df = schemas.read_results_frame(path)
        return {(r["Rephrased Main Part"], r["Response Format"]):
                {c: r[c]
                 for c in exact_cols + float_cols + ["Log Probabilities"]}
                for _, r in df.iterrows()}

    def floats_close(g, w):
        if pd.isna(g) and pd.isna(w):
            return True
        try:
            return abs(float(g) - float(w)) <= FLOAT_TOL
        except (TypeError, ValueError):
            return g == w

    def logprobs_close(g, w):
        # The stored top-20 map is a dict repr; same ids, values within
        # tolerance (string-equal fast path first).
        if g == w or (pd.isna(g) and pd.isna(w)):
            return True
        try:
            gd, wd = ast.literal_eval(str(g)), ast.literal_eval(str(w))
        except (ValueError, SyntaxError):
            return False
        return (isinstance(gd, dict) and isinstance(wd, dict)
                and set(gd) == set(wd)
                and all(abs(gd[k] - wd[k]) <= FLOAT_TOL for k in gd))

    def sweep_twice(cascade_on, td):
        eng = engine(cascade_on)
        for leg in ("cold", "warm"):    # pass 2 resumes trunks paged-warm
            run_perturbation_sweep(eng, "cascade-bench", lp, perts,
                                   td / f"{cascade_on}-{leg}.csv",
                                   checkpoint_every=6)
        return eng

    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        # Off-chip the engine gate requires the kernel route to exist:
        # arm the tier-1 interpreter hook for the whole comparison (the
        # OFF engine ignores it — cascade_prefill=False wins first).
        prev_hook = decoder_mod.CASCADE_INTERPRET_ON_CPU
        if not on_accel:
            decoder_mod.CASCADE_INTERPRET_ON_CPU = True
        try:
            eng_on = sweep_twice(True, td)
            eng_off = sweep_twice(False, td)
        finally:
            decoder_mod.CASCADE_INTERPRET_ON_CPU = prev_hook
        parity_ok = True
        cells = {}
        for leg in ("cold", "warm"):
            on = rows_by_key(td / f"True-{leg}.csv")
            off = rows_by_key(td / f"False-{leg}.csv")
            cells = off
            if set(on) != set(off):
                parity_ok = False
                continue
            for k, want in off.items():
                got = on[k]
                for c in exact_cols:
                    if not (pd.isna(got[c]) and pd.isna(want[c])) \
                            and got[c] != want[c]:
                        parity_ok = False
                for c in float_cols:
                    if not floats_close(got[c], want[c]):
                        parity_ok = False
                if not logprobs_close(got["Log Probabilities"],
                                      want["Log Probabilities"]):
                    parity_ok = False
        assert parity_ok, ("cascade ON vs OFF per-cell results diverged "
                           "past the PR-7 parity bar")

        s = eng_on.cascade_stats
        assert s.cascade_dispatches > 0, \
            "the shared-trunk grid never took the cascade path"
        assert s.prefix_flops_saved > 0, "zero trunk prefill FLOPs deduped"
        assert eng_off.cascade_stats.cascade_dispatches == 0, \
            "the cascade-OFF engine cascaded"

        # Plateau projection over both passes: the grid's useful prefill
        # FLOPs paid densely (every row re-prefills its full prompt) vs
        # with the cascade (CascadeStats' analytic dedup subtracted) —
        # the deduped trunk work raises prefill MFU and p/s by exactly
        # the useful-FLOPs ratio at fixed wall time per remaining FLOP.
        rt = eng_on.rt
        dense_prefill = other = 0.0
        for main, _fmt in cells:
            for fmt, new in ((bin_fmt, rt.sweep_decode_tokens),
                             (conf_fmt, rt.sweep_confidence_tokens)):
                seq = len(f"{main} {fmt}".split())   # FakeTokenizer words
                split = profiling.scoring_step_flops_split(cfg, 1, seq, new)
                dense_prefill += split["prefill"]
                other += split["decode"] + split["readout"]
        dense_prefill *= 2      # two passes
        other *= 2
        casc_prefill = dense_prefill - s.prefix_flops_saved
        assert casc_prefill > 0, "saved more prefill FLOPs than exist"
        implied_mfu = PLATEAU_MFU * dense_prefill / casc_prefill
        implied_ps = (PLATEAU_PS * (dense_prefill + other)
                      / (casc_prefill + other))
        assert implied_mfu > PLATEAU_MFU, (
            f"prefill-phase MFU did not clear the plateau "
            f"({implied_mfu:.2f} <= {PLATEAU_MFU})")
        assert implied_ps > PLATEAU_PS, (
            f"isolated-step p/s did not clear the plateau "
            f"({implied_ps:.2f} <= {PLATEAU_PS})")

    return {
        "cascade_dispatches": int(s.cascade_dispatches),
        "dense_fallbacks": int(s.dense_fallbacks),
        "trunk_rows_deduped": int(s.trunk_rows_deduped),
        "prefix_flops_saved": float(s.prefix_flops_saved),
        "prefill_flops_dense": float(dense_prefill),
        "prefill_flops_cascade": float(casc_prefill),
        "prefill_flops_ratio": round(dense_prefill / casc_prefill, 3),
        "implied_prefill_mfu_pct": round(implied_mfu, 2),
        "implied_step_ps": round(implied_ps, 2),
        "plateau_mfu_pct": PLATEAU_MFU,
        "plateau_ps": PLATEAU_PS,
        "parity_ok": bool(parity_ok),
    }


def _cascade_decode_bench(on_accel: bool):
    """Cascade-decode mode (PR 17): the shared-trunk warm grid's DECODE
    phase — the same dispatch batch run cold and paged-warm with the
    trunk-aware flash-decode splits ON vs OFF. Gates asserted before
    reporting:

    - PARITY: per-row payloads argmax-identical between ON and OFF on
      BOTH passes (ints exact, floats within FLOAT_TOL — on the chip
      the trunk kernels are bitwise; under the CPU interpreter XLA's
      shape-dependent SIMD tails allow ulp drift);
    - the dedup engaged: nonzero cascade-decode dispatches and analytic
      trunk bytes deduped on the ON engine, zero on the OFF engine;
    - the HEADLINE gate: decode-phase attention HBM bytes per row,
      with the flat kernels streaming every row's full cache each step
      vs the trunk splits loaded once per dispatch-step, reduced by
      >= 1.3x. The byte model mirrors the kernel's own static split
      ladder (profiling.cascade_decode_bytes_saved), so the ratio is
      the traffic the lowered kernel really removes — on TPU the same
      ratio rides the measured step.
    """
    import jax
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder as decoder_mod
    from lir_tpu.models.registry import ModelConfig

    FLOAT_TOL = 1e-4
    MIN_RATIO = 1.3
    ROWS, BUCKET, TRUNK, SFX = 8, 128, 96, 8
    NEW, CONF = 3, 4

    cfg = ModelConfig(name="cascdec-bench", vocab_size=FakeTokenizer.VOCAB,
                      hidden_size=32, n_layers=2, n_heads=4, n_kv_heads=2,
                      intermediate_size=64, max_seq_len=512)
    params = decoder_mod.init_params(cfg, jax.random.PRNGKey(53))
    rng = np.random.default_rng(59)
    trunk_ids = [int(x) for x in rng.integers(3, 200, TRUNK)]
    rows = [trunk_ids + [int(x) for x in rng.integers(3, 200, 6 - (r % 3))]
            for r in range(ROWS)]
    bins = [r + [5, 6] for r in rows]
    conf = [r + [7, 8] for r in rows]
    t1 = np.asarray([5] * ROWS, np.int32)
    t2 = np.asarray([9] * ROWS, np.int32)

    def engine(decode_on):
        # prefix_cache=True so the second dispatch resumes the trunk
        # paged-warm — the workload regime where decode dominates.
        return ScoringEngine(params, cfg, FakeTokenizer(), RuntimeConfig(
            batch_size=ROWS, max_seq_len=512, prefix_cache=True,
            prefix_cache_pages=256, cascade_decode=decode_on))

    def dispatch(eng):
        return eng.decode_fused_shared(
            [""] * ROWS, [""] * ROWS, t1, t2, new_tokens=NEW,
            conf_tokens=CONF, pretokenized_a=bins, pretokenized_b=conf,
            bucket=BUCKET, sfx_buckets_ab=(SFX, SFX), reuse_cache=True,
            n_real=ROWS)

    prev_hook = decoder_mod.FUSED_DECODE_INTERPRET_ON_CPU
    if not on_accel:
        # Off-chip the decode gate requires the fused kernel route to
        # exist: arm the tier-1 interpreter hook for the comparison
        # (the OFF engine ignores it — cascade_decode=False wins first).
        decoder_mod.FUSED_DECODE_INTERPRET_ON_CPU = True
    try:
        eng_on = engine(True)
        on_cold, on_warm = dispatch(eng_on), dispatch(eng_on)
        eng_off = engine(False)
        off_cold, off_warm = dispatch(eng_off), dispatch(eng_off)
    finally:
        decoder_mod.FUSED_DECODE_INTERPRET_ON_CPU = prev_hook

    parity_ok = True
    for got, want in ((on_cold, off_cold), (on_warm, off_warm)):
        for a, b in zip(got, want):
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
                x, y = np.asarray(x), np.asarray(y)
                if np.issubdtype(x.dtype, np.floating):
                    parity_ok &= bool(np.allclose(x, y, atol=FLOAT_TOL))
                else:
                    parity_ok &= bool((x == y).all())
    assert parity_ok, ("cascade-decode ON vs OFF payloads diverged past "
                       "the argmax parity bar")

    s = eng_on.cascade_stats
    n_disp = int(s.cascade_decode_dispatches)
    saved = float(s.trunk_bytes_deduped)
    assert n_disp >= 2, "cold + warm dispatches did not both cascade"
    assert saved > 0, "zero trunk bytes deduped"
    assert eng_off.cascade_stats.cascade_decode_dispatches == 0, \
        "the cascade-decode-OFF engine still deduped"

    # Decode-phase attention HBM bytes: the flat kernels stream every
    # row's full cache extent (K + V) each decode step.
    t0 = BUCKET + max(SFX + NEW, SFX + CONF)
    steps = NEW + CONF
    per_row_step = 2 * cfg.n_kv_heads * t0 * cfg.head_dim * 4 * cfg.n_layers
    flat_bytes = float(per_row_step * ROWS * steps * n_disp)
    dedup_bytes = flat_bytes - saved
    assert dedup_bytes > 0, "deduped more bytes than the flat kernel reads"
    ratio = flat_bytes / dedup_bytes
    assert ratio >= MIN_RATIO, (
        f"decode-phase HBM-bytes/row reduction {ratio:.3f}x below the "
        f"{MIN_RATIO}x bar")

    return {
        "cascade_decode_dispatches": n_disp,
        "trunk_bytes_deduped": saved,
        "decode_attn_bytes_flat": flat_bytes,
        "decode_attn_bytes_dedup": dedup_bytes,
        "hbm_bytes_per_row_reduction": round(ratio, 3),
        "min_ratio": MIN_RATIO,
        "rows": ROWS,
        "trunk_tokens": TRUNK,
        "cache_extent": t0,
        "parity_ok": bool(parity_ok),
    }


def _elastic_bench(on_accel: bool):
    """Elastic-serving mode (ROADMAP item 1): the replica-kill chaos
    proof, online and offline.

    ONLINE — an open-loop fleet trace over 3 config-identical replica
    servers behind the ReplicaRouter, with replica r1 KILLED mid-run by
    a seeded ``replica_kill`` schedule (the router observes the death
    first, then the in-flight dispatch dies — an abrupt host loss) and
    revived two waves later. Gates asserted before reporting:

    - ZERO requests dropped (every future resolves "ok") and ZERO
      double-resolved (resolve-once futures + unique ids; the zombie's
      late payloads are counted and dropped);
    - goodput after the kill >= 0.6x the 3-replica goodput (capacity
      fell 1/3; medians over per-wave client time so one scheduler
      hiccup can't fake a failure) and RECOVERING after the rejoin
      (>= 0.8x the post-kill goodput — on the CPU smoke the replicas
      share cores, so the interesting content is the zero-loss
      accounting; on a real fleet the ratios track capacity);
    - replica-independence: the same probe scored directly on each
      replica returns BITWISE-identical payloads (PAPER.md's axis
      results cannot depend on which replica scored a row).

    OFFLINE — the leased sweep: a static-shard run's accumulator vs a
    leased run killed mid-sweep, whose expired leases a SECOND holder
    steals on resume. The merged accumulator must be BITWISE-identical
    to the uninterrupted static run (idempotent slot folds +
    identical-overlap union)."""
    import tempfile

    import numpy as np

    from lir_tpu import faults
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RouterConfig, RuntimeConfig, ServeConfig
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine import lease as lease_mod
    from lir_tpu.engine import stream_stats as stream_mod
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ReplicaRouter, ScoringServer, ServeRequest

    n_waves, per_wave, batch = 12, 8, 4
    mcfg = ModelConfig(name="elastic-bench",
                       vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=64 if on_accel else 32, n_layers=1,
                       n_heads=2, intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(mcfg, jax.random.PRNGKey(23))
    serve_cfg = ServeConfig(queue_depth=256,
                            classes=(("elastic", 3600.0),),
                            default_class="elastic", linger_s=0.002)

    def _server():
        engine = ScoringEngine(params, mcfg, FakeTokenizer(),
                               RuntimeConfig(batch_size=batch,
                                             max_seq_len=256))
        return ScoringServer(engine, "elastic-bench", serve_cfg)

    rng = np.random.default_rng(31)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement").split()

    def request(w, j):
        body = (" ".join(rng.choice(words) for _ in range(10))
                + f" wave {w} q {j} ?")
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="elastic", request_id=f"w{w}q{j}")

    servers = [_server().start() for _ in range(3)]
    # Warm every replica through BOTH cache-handoff variants so the
    # timed waves measure serving, not compiles.
    for si, s in enumerate(servers):
        for k in range(2):
            assert s.submit(request(90 + si, k)).result(600) \
                .status == "ok"
    router = ReplicaRouter(
        [(f"r{i}", s) for i, s in enumerate(servers)],
        config=RouterConfig(replica_failure_threshold=1,
                            replica_cooldown_s=0.3,
                            cache_entries=0)).start()
    kill_plan = faults.FaultPlan(seed=13, schedules={
        "replica": faults.SiteSchedule.replica_kill_at(0, "r1")})

    results, wave_s = [], []
    kill_wave = n_waves // 3          # kill fires INSIDE this wave
    revive_wave = 2 * n_waves // 3
    try:
        for w in range(n_waves):
            if w == kill_wave:
                faults.wrap_replica(router, "r1", kill_plan)
            if w == revive_wave:
                router.revive_replica("r1")
                time.sleep(0.35)      # past the breaker cooldown
            t0 = time.perf_counter()
            futs = [router.submit(request(w, j))
                    for j in range(per_wave)]
            results += [f.result(600) for f in futs]
            wave_s.append(time.perf_counter() - t0)
        # Replica-independence: one probe through each replica
        # directly, payloads bitwise-equal.
        probe = request(80, 0)
        fields = ("model_response", "model_confidence_response",
                  "token_1_prob", "token_2_prob", "log_probabilities",
                  "confidence_value", "weighted_confidence")
        direct = []
        for s in servers:
            r = s.submit(probe).result(600)
            assert r.status == "ok", r.status
            direct.append(tuple(getattr(r, f) for f in fields))
    finally:
        router.stop()
        for s in servers:
            s.stop()

    assert kill_plan.injected("replica") == 1, "replica_kill never fired"
    assert all(r.status == "ok" for r in results), (
        f"dropped requests: "
        f"{[r.status for r in results if r.status != 'ok'][:4]}")
    ids = [r.request_id for r in results]
    assert len(set(ids)) == len(ids) == n_waves * per_wave, (
        "requests dropped or double-resolved")
    assert router.stats.completed == n_waves * per_wave
    assert direct[0] == direct[1] == direct[2], (
        "replicas are not result-identical")

    med = lambda xs: float(np.median(xs))  # noqa: E731
    g_before = per_wave / med(wave_s[:kill_wave])
    g_after = per_wave / med(wave_s[kill_wave:revive_wave])
    g_recovered = per_wave / med(wave_s[revive_wave:])
    assert g_after >= 0.6 * g_before, (
        f"goodput after the kill {g_after:.2f} < 0.6x the 3-replica "
        f"{g_before:.2f}")
    assert g_recovered >= 0.8 * g_after, (
        f"goodput did not recover after the rejoin: {g_recovered:.2f} "
        f"vs post-kill {g_after:.2f}")

    # -- offline: leased sweep, kill + steal, accumulator bitwise -------------
    sweep_cells = 10
    rng2 = np.random.default_rng(37)

    def _text(n):
        return " ".join(rng2.choice(words) for _ in range(n)) + " ?"

    lp = (LegalPrompt(main=_text(10),
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    perts = ([_text(10 if i % 2 else 20)
              for i in range(sweep_cells - 1)],)

    def _sweep_engine(lease: bool):
        return ScoringEngine(
            params, mcfg, FakeTokenizer(),
            RuntimeConfig(batch_size=batch, max_seq_len=256,
                          piggyback_prefill=False, lease_shards=lease,
                          lease_ttl_s=0.05, lease_cells_per_shard=3))

    lease_bitwise = False
    steals = 0
    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        run_perturbation_sweep(_sweep_engine(False), "elastic", lp,
                               perts, td / "static.csv",
                               checkpoint_every=4)
        acc_static = stream_mod.load_accum(
            (td / "static.csv").with_suffix(stream_mod.ACCUM_SUFFIX))
        engine = _sweep_engine(True)
        plan = faults.FaultPlan(seed=9, schedules={
            "dispatch": faults.SiteSchedule.kill_at(1)})
        faults.wrap_engine(engine, plan)
        out = td / "leased.csv"
        try:
            run_perturbation_sweep(engine, "elastic", lp, perts, out,
                                   checkpoint_every=4)
            raise AssertionError("scheduled kill never fired")
        except faults.InjectedPreemption:
            pass
        time.sleep(0.06)              # the dead holder's leases expire
        saved_idx = jax.process_index
        jax.process_index = lambda: 1   # the stealing holder
        try:
            run_perturbation_sweep(_sweep_engine(True), "elastic", lp,
                                   perts, out, checkpoint_every=4)
        finally:
            jax.process_index = saved_idx
        acc = stream_mod.load_accum(
            out.with_suffix(stream_mod.ACCUM_SUFFIX))
        lease_bitwise = (
            acc is not None and acc_static is not None
            and np.array_equal(acc_static.filled, acc.filled)
            and np.array_equal(acc_static.rel, acc.rel, equal_nan=True)
            and np.array_equal(acc_static.conf, acc.conf,
                               equal_nan=True)
            and np.array_equal(acc_static.dec, acc.dec))
        assert lease_bitwise, (
            "leased steal-resumed accumulator is NOT bitwise-identical "
            "to the uninterrupted static run")
        check = lease_mod.LeaseManager(
            out.with_suffix(lease_mod.LEASE_SUFFIX), "checker")
        n_shards = -(-sweep_cells // 3)
        holders = {(check.record(s) or {}).get("holder")
                   for s in range(n_shards)}
        assert "host1" in holders, "no shard finished by the stealer"
        steals = sum(1 for s in range(n_shards)
                     if (check.record(s) or {}).get("holder") == "host1")

    return {
        "replicas": 3,
        "waves": n_waves,
        "requests_per_wave": per_wave,
        "killed_replica": "r1",
        "requests_total": n_waves * per_wave,
        "requests_dropped": 0,
        "requests_double_resolved": 0,
        "re_admitted": int(router.stats.re_admitted),
        "failovers": int(router.stats.failovers),
        "zombie_payloads": int(router.stats.zombie_payloads),
        "goodput_3_replicas_p_s": round(g_before, 3),
        "goodput_after_kill_p_s": round(g_after, 3),
        "goodput_recovered_p_s": round(g_recovered, 3),
        "after_kill_vs_before": round(g_after / g_before, 3),
        "recovered_vs_after_kill": round(g_recovered / g_after, 3),
        "replica_payloads_bitwise": True,
        "per_replica": dict(router.stats.per_replica),
        "lease_accum_bitwise_vs_static": bool(lease_bitwise),
        "lease_shards_stolen": int(steals),
    }


def _disagg_bench(on_accel: bool):
    """Disaggregated prefill/decode mode (ROADMAP item 2; serve/migrate
    .py): the SAME prefill-heavy open-loop trace served twice at EQUAL
    chip count — 3 colocated replicas vs 1 prefill-role + 2 decode-role
    replicas with KV-page migration — and the interactive tail compared.

    The trace is the paper's production shape: a stream of short
    interactive probes (warm shared trunk, decode-dominated) with long
    fresh-trunk batch prompts arriving between them. Colocated, a batch
    prompt's full-bucket quadratic prefill occupies whichever replica
    it lands on, and every interactive request arriving there during
    the dispatch waits it out — prefill queueing IS the interactive
    tail. Disaggregated, the prefill runs on the prefill replica, only
    the migrated-page remainder window reaches the decode replicas, and
    the interactive tail collapses.

    Gates asserted before reporting:

    - p99 interactive (decode-path) latency at least 1.3x better
      disaggregated than colocated (CPU smoke gate; on real chips the
      ratio tracks the prefill/decode cost gap);
    - ZERO dropped requests in both runs, every future "ok";
    - per-request payloads BITWISE-identical across the two servers
      (migrated-page decode == local-prefill decode);
    - nonzero pages migrated, with the hidden/exposed transfer-second
      split reported."""
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import (MigrationConfig, RouterConfig,
                                RuntimeConfig, ServeConfig)
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ReplicaRouter, ScoringServer, ServeRequest

    batch = 4
    n_heavy, inter_per_heavy = 6, 6
    n_interactive = n_heavy * inter_per_heavy
    # Big enough that a full-bucket prefill visibly occupies a replica
    # on the CPU smoke (the contrast under test is prefill-dispatch
    # occupancy vs decode-path work, the same shape it takes on chips).
    mcfg = ModelConfig(name="disagg-bench",
                       vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=128, n_layers=4, n_heads=4,
                       intermediate_size=256, max_seq_len=512)
    params = decoder.init_params(mcfg, jax.random.PRNGKey(29))
    serve_cfg = ServeConfig(queue_depth=256, cache_entries=0,
                            classes=(("interactive", 3600.0),
                                     ("batch", 3600.0)),
                            default_class="batch", linger_s=0.002)

    def _server():
        # spec decode OFF: orthogonal to the disagg contrast, and it
        # doubles the executable surface the warmup must cover.
        engine = ScoringEngine(params, mcfg, FakeTokenizer(),
                               RuntimeConfig(batch_size=batch,
                                             max_seq_len=512,
                                             spec_decode=False))
        return ScoringServer(engine, "disagg-bench", serve_cfg)

    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible").split()
    rng = np.random.default_rng(41)
    # Interactive probes: ONE shared short trunk (warm after the first
    # ask, below the migration threshold so they always score directly
    # on a decode replica); heavy batch prompts: a FRESH long trunk
    # each (full prefill somewhere, every time). Fixed word counts keep
    # every request of a kind the same token shape, so the warmup
    # compiles cover the whole timed trace.
    inter_trunk = " ".join(rng.choice(words) for _ in range(24))

    def interactive(i):
        body = f"{inter_trunk} probe {i}"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="interactive", request_id=f"i{i}")

    def heavy(i, tag=""):
        trunk = " ".join(rng.choice(words) for _ in range(300))
        body = f"{trunk} matter {tag}{i}"
        return ServeRequest(
            binary_prompt=f"{body} Answer Yes or No .",
            confidence_prompt=f"{body} Give a number from 0 to 100 .",
            klass="batch", request_id=f"h{tag}{i}")

    # One deterministic arrival schedule, replayed for both configs:
    # each fresh-trunk heavy arrives, then a burst of interactive
    # probes lands WHILE its prefill dispatch is (colocated) occupying
    # a replica — prefill queueing as the interactive tail's cause.
    events = []
    for h in range(n_heavy):
        events.append(("h", heavy(h), 0.25))
        for j in range(inter_per_heavy):
            events.append(("i", interactive(h * inter_per_heavy + j),
                           0.04))
    mig_cfg = MigrationConfig(min_prefix_tokens=48, chunk_pages=8,
                              timeout_s=60.0)

    def run(roles):
        servers = [_server().start() for _ in range(3)]
        ids = ["pre", "d0", "d1"] if roles else ["r0", "r1", "r2"]
        router = ReplicaRouter(
            list(zip(ids, servers)),
            config=RouterConfig(cache_entries=0, tick_s=0.01),
            roles=({"pre": "prefill", "d0": "decode", "d1": "decode"}
                   if roles else None),
            migrate=(mig_cfg if roles
                     else MigrationConfig(enabled=False))).start()
        try:
            # Warm every executable shape out of the timed window —
            # in BURSTS, so each replica forms consecutive same-shape
            # dispatches and compiles both cache-handoff variants
            # (scratchless AND donated-scratch); on the disagg config
            # the bursts also compile the prefill-only program and the
            # migrated-page window executables on every decode replica.
            for w in range(2):
                hf = [router.submit(heavy(10 * w + k, tag="w"))
                      for k in range(6)]
                assert all(f.result(900).status == "ok" for f in hf)
                jf = [router.submit(interactive(900 + 20 * w + k))
                      for k in range(12)]
                assert all(f.result(900).status == "ok" for f in jf)
            futs = []
            for kind, req, gap in events:
                time.sleep(float(gap))
                futs.append((kind, req.request_id, router.submit(req)))
            res = [(kind, rid, f.result(900)) for kind, rid, f in futs]
        finally:
            router.stop()
            for s in servers:
                s.stop()
        assert all(r.status == "ok" for _, _, r in res), (
            [r.status for _, _, r in res if r.status != "ok"][:4])
        inter_lat = [r.latency_s for kind, _, r in res if kind == "i"]
        payloads = {rid: tuple(
            getattr(r, f) for f in ("model_response",
                                    "model_confidence_response",
                                    "token_1_prob", "token_2_prob",
                                    "log_probabilities",
                                    "confidence_value",
                                    "weighted_confidence"))
            for _, rid, r in res}
        return inter_lat, payloads, router.migrate_stats.summary()

    colo_lat, colo_payloads, _ = run(roles=False)
    dis_lat, dis_payloads, mig = run(roles=True)

    assert set(colo_payloads) == set(dis_payloads)
    mismatched = [rid for rid in colo_payloads
                  if colo_payloads[rid] != dis_payloads[rid]]
    assert not mismatched, (
        f"payloads differ between colocated and disaggregated servers: "
        f"{mismatched[:4]}")
    assert mig["pages_migrated"] > 0, "no pages migrated"
    p99_colo = float(np.percentile(colo_lat, 99))
    p99_dis = float(np.percentile(dis_lat, 99))
    ratio = p99_colo / max(p99_dis, 1e-9)
    assert ratio >= 1.3, (
        f"disaggregated p99 decode latency {p99_dis:.3f}s is only "
        f"{ratio:.2f}x better than colocated {p99_colo:.3f}s (< 1.3x)")
    return {
        "replicas": 3,
        "prefill_replicas": 1,
        "interactive_requests": n_interactive,
        "heavy_requests": n_heavy,
        "requests_dropped": 0,
        "p99_decode_latency_colocated_s": round(p99_colo, 4),
        "p99_decode_latency_disagg_s": round(p99_dis, 4),
        "p99_decode_latency_ratio": round(ratio, 2),
        "p50_decode_latency_colocated_s": round(
            float(np.percentile(colo_lat, 50)), 4),
        "p50_decode_latency_disagg_s": round(
            float(np.percentile(dis_lat, 50)), 4),
        "pages_migrated": mig["pages_migrated"],
        "migrations": mig["migrations"],
        "migration_s_hidden": mig["migration_s_hidden"],
        "migration_s_exposed": mig["migration_s_exposed"],
        "refetch_fallbacks": mig["refetch_fallbacks"],
        "cluster_tree_hits": mig["cluster_tree_hits"],
        "payloads_bitwise": True,
    }


def _memory_bench(on_accel: bool):
    """Memory-governance mode (engine/hbm.py): the OOM-squeeze proof as
    a measured ratio. ONE grid is swept twice on config-identical
    engines — unpressured, then with a seeded ``hbm_squeeze`` cutting
    the HBM governor's ledger budget to 5% for a few dispatch ticks
    mid-run (faults.wrap_governor). Gates asserted before reporting:

    - ZERO crashed dispatches: the squeezed sweep completes the full
      grid (no lost/duplicated cells, no quarantines);
    - every engaged degradation rung is REVERSIBLE: rung_downs ==
      rung_ups once the squeeze clears, ladder back at level 0;
    - per-cell rows BITWISE-identical to the unpressured run — no
      rung is allowed to change results;
    - goodput under the squeeze >= 0.6x unpressured (the ladder's
      rungs — pages evicted, piggyback/spec off — cost throughput,
      never correctness; on the CPU smoke the ratio is dominated by
      noise, so the gate is deliberately loose — the content is the
      zero-crash + bitwise accounting)."""
    import tempfile

    import numpy as np
    import pandas as pd

    from lir_tpu import faults
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import GovernorConfig, RuntimeConfig
    from lir_tpu.data import schemas
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig

    n_cells, batch = 24, 4
    mcfg = ModelConfig(name="memory-bench",
                       vocab_size=FakeTokenizer.VOCAB,
                       hidden_size=64 if on_accel else 32, n_layers=1,
                       n_heads=2, intermediate_size=64, max_seq_len=256)
    params = decoder.init_params(mcfg, jax.random.PRNGKey(41))
    rng = np.random.default_rng(43)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement").split()

    def _text(n):
        return " ".join(rng.choice(words) for _ in range(n)) + " ?"

    lp = (LegalPrompt(main=_text(10),
                      response_format="Answer Yes or No .",
                      target_tokens=("Yes", "No"),
                      confidence_format="Give a number from 0 to 100 ."),)
    perts = ([_text(10 if i % 2 else 24) for i in range(n_cells - 1)],)

    def _engine():
        # piggyback OFF: the squeezed pass is compared BITWISE against
        # the unpressured pass, so both must run the plain dispatch
        # path (chaos_smoke's rule); sustain 1 so the grid's handful
        # of dispatch ticks walks the ladder.
        return ScoringEngine(
            params, mcfg, FakeTokenizer(),
            RuntimeConfig(batch_size=batch, max_seq_len=256,
                          piggyback_prefill=False),
            governor_config=GovernorConfig(sustain_ticks=1))

    value_cols = ("Token_1_Prob", "Token_2_Prob", "Confidence Value",
                  "Weighted Confidence", "Model Response",
                  "Model Confidence Response", "Log Probabilities")
    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        # Warm pass compiles every bucket executable so both timed
        # passes measure dispatching, not traces.
        run_perturbation_sweep(_engine(), "memory", lp, perts,
                               td / "warm.csv", checkpoint_every=8)
        t0 = time.perf_counter()
        run_perturbation_sweep(_engine(), "memory", lp, perts,
                               td / "base.csv", checkpoint_every=8)
        base_s = time.perf_counter() - t0
        base_df = schemas.read_results_frame(td / "base.csv")
        base_by_key = {
            (r["Rephrased Main Part"], r["Response Format"],
             r["Confidence Format"]): tuple(r[c] for c in value_cols)
            for _, r in base_df.iterrows()}

        engine = _engine()
        plan = faults.FaultPlan(seed=47, schedules={
            "hbm": faults.SiteSchedule.hbm_squeeze_at(1, frac=0.05,
                                                      calls=4)})
        faults.wrap_governor(engine.governor, plan)
        t0 = time.perf_counter()
        run_perturbation_sweep(engine, "memory", lp, perts,
                               td / "squeezed.csv", checkpoint_every=8)
        squeezed_s = time.perf_counter() - t0
        gov = engine.governor

        assert plan.injected("hbm") == 1, "hbm_squeeze never fired"
        assert gov.stats.rung_downs, "squeeze never walked the ladder"
        for _ in range(16):          # the next dispatches of a longer
            if gov.level == 0:       # session re-arm the ladder
                break
            gov.tick()
        assert gov.level == 0, f"ladder stuck at level {gov.level}"
        assert gov.stats.rung_ups == gov.stats.rung_downs, (
            f"rungs not reversible: downs {gov.stats.rung_downs} vs "
            f"ups {gov.stats.rung_ups}")

        df = schemas.read_results_frame(td / "squeezed.csv")
        keys = list(zip(df["Rephrased Main Part"],
                        df["Response Format"], df["Confidence Format"]))
        assert len(keys) == n_cells and len(set(keys)) == n_cells, (
            f"squeezed sweep crashed dispatches: {len(keys)} rows, "
            f"{len(set(keys))} unique, expected {n_cells}")
        for _, row in df.iterrows():
            k = (row["Rephrased Main Part"], row["Response Format"],
                 row["Confidence Format"])
            want = base_by_key[k]
            got = tuple(row[c] for c in value_cols)
            for g, w in zip(got, want):
                if pd.isna(g) and pd.isna(w):
                    continue
                assert g == w, (
                    f"squeezed row differs from unpressured: {g!r} != "
                    f"{w!r} for {k[0][:40]}")

        g_base = n_cells / base_s
        g_squeezed = n_cells / squeezed_s
        assert g_squeezed >= 0.6 * g_base, (
            f"goodput under the squeeze {g_squeezed:.2f} p/s < 0.6x "
            f"unpressured {g_base:.2f} p/s")
        return {
            "cells": n_cells,
            "goodput_unpressured_p_s": round(g_base, 3),
            "goodput_squeezed_p_s": round(g_squeezed, 3),
            "squeezed_vs_unpressured": round(g_squeezed / g_base, 3),
            "crashed_dispatches": 0,
            "rows_bitwise": True,
            "squeezes": int(gov.stats.squeezes),
            "rung_downs": dict(gov.stats.rung_downs),
            "rung_ups": dict(gov.stats.rung_ups),
            "ladder_level_final": int(gov.level),
        }


def _tiered_bench(on_accel: bool):
    """Tiered-memory mode (serve/tiers.py): the capacity-robustness win
    as a measured ratio. A shared-prefix grid whose radix working set is
    ~3x the HBM page pool is served cold then re-served warm on two
    config-identical servers — tiers OFF (evict-and-recompute: the pool
    churns, every warm re-ask re-prefills its evicted trunk) and tiers
    ON (the cold pass's trunks were demoted down the HBM -> host ->
    disk ladder, so every warm re-ask promotes its trunk back through
    the paged-warm import instead of recomputing it). Gates asserted
    before reporting:

    - ZERO crashed dispatches: every request on every pass resolves
      "ok", none dropped or double-resolved;
    - warm goodput tiered >= 1.3x evict-and-recompute;
    - every payload on every tiered pass BITWISE-identical to the
      untiered server's — the ladder is invisible in results;
    - kill/restart leg: the tiered server + engine are DISCARDED (only
      the disk directory survives), a fresh server restart-warm
      re-seeds from the index and re-serves the sentinel grid with
      >= 90% of prefix prefill tokens avoided, payloads bitwise."""
    import tempfile

    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig, ServeConfig, TierConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.models import decoder
    from lir_tpu.models.registry import ModelConfig
    from lir_tpu.serve import ScoringServer, ServeRequest

    n_bases, per_base, base_words, pool_pages = 6, 2, 280, 34
    cells = n_bases * per_base
    mcfg = ModelConfig(name="tiered-bench",
                       vocab_size=FakeTokenizer.VOCAB, hidden_size=64,
                       n_layers=2, n_heads=2, intermediate_size=128,
                       max_seq_len=512)
    params = decoder.init_params(mcfg, jax.random.PRNGKey(53))
    rng = np.random.default_rng(59)
    words = ("coverage policy flood water damage claim insurer premium "
             "exclusion endorsement peril deductible adjuster settle "
             "liability clause binding interpret statute meaning").split()
    bases = [" ".join(rng.choice(words) for _ in range(base_words))
             for _ in range(n_bases)]

    # Round-robin across bases: by the time a trunk is re-asked, five
    # others (>> the pool) have churned through — the untiered warm
    # pass recomputes, the tiered one promotes.
    reqs = []
    for j in range(per_base):
        for b in range(n_bases):
            body = f"{bases[b]} case {b}x{j} ?"
            reqs.append(ServeRequest(
                binary_prompt=f"{body} Answer Yes or No .",
                confidence_prompt=f"{body} Give a number from 0 to "
                                  f"100 .",
                klass="bench", request_id=f"{b}x{j}"))

    def engine():
        return ScoringEngine(params, mcfg, FakeTokenizer(),
                             RuntimeConfig(batch_size=4, max_seq_len=512,
                                           prefix_cache=True,
                                           prefix_cache_pages=pool_pages))

    # cache_entries=0: the warm re-asks are exact repeats, and the mode
    # measures the KV ladder, not the result-dedup cache.
    scfg = ServeConfig(queue_depth=cells + 8, prefix_cache=True,
                       cache_entries=0, classes=(("bench", 600.0),),
                       default_class="bench", linger_s=0.01)

    def one_pass(srv, timed=False):
        # Closed-loop sequential: the untiered pool's LRU is forced to
        # churn and the tiered promotes land one trunk at a time (the
        # pool holds ~2 trunks — concurrent promotes would evict each
        # other, which is the working-set-3x-HBM point).
        t0 = time.perf_counter()
        out = [srv.submit(r).result(timeout=600) for r in reqs]
        dt = time.perf_counter() - t0
        assert all(r.status == "ok" for r in out), (
            [r.status for r in out])
        assert len({r.request_id for r in out}) == cells, (
            "dropped/double-resolved")
        return (out, dt) if timed else out

    fields = ("model_response", "model_confidence_response",
              "token_1_prob", "token_2_prob", "log_probabilities",
              "confidence_value", "weighted_confidence")

    def assert_bitwise(name, got, ref):
        for g, r in zip(got, ref):
            for f in fields:
                assert getattr(g, f) == getattr(r, f), (
                    f"{name} payload field {f} differs from untiered "
                    f"on request {g.request_id}")

    flat_srv = ScoringServer(engine(), "tiered-bench", scfg).start()
    base = one_pass(flat_srv)                 # cold + compiles
    one_pass(flat_srv)                        # warm-shape compile pass
    flat_out, flat_dt = min((one_pass(flat_srv, timed=True)
                             for _ in range(2)), key=lambda t: t[1])
    flat_srv.stop()
    assert_bitwise("untiered-warm", flat_out, base)

    with tempfile.TemporaryDirectory(prefix="tiered_bench_") as tmp:
        # Tiny host pool: every demotion spills straight through to the
        # disk tier, so the kill/restart leg below has the full working
        # set to re-seed from.
        tcfg = TierConfig(enabled=True, disk_dir=tmp,
                          host_budget_mb=0.0001, disk_timeout_s=30.0)
        srv = ScoringServer(engine(), "tiered-bench", scfg,
                            tiers=tcfg).start()
        store = srv.tiers

        def demote_all():
            srv.submit_page_op(
                lambda eng: [store.demote(eng, n_pages=999)
                             for _ in range(8)]).result(60)

        # Cold pass with the evict_pages rung engaged after every
        # request (sustained pressure: the working set is 3x the pool,
        # so without demotion the pool's own insert-time eviction
        # would DELETE most trunks before they ever reach the ladder).
        cold = []
        for r in reqs:
            cold.append(srv.submit(r).result(timeout=600))
            demote_all()
        assert all(r.status == "ok" for r in cold)
        assert_bitwise("tiered-cold", cold, base)
        one_pass(srv)              # warm-shape compile pass (promotes)
        tiered_out, tiered_dt = min((one_pass(srv, timed=True)
                                     for _ in range(2)),
                                    key=lambda t: t[1])
        assert_bitwise("tiered-warm", tiered_out, base)
        live = store.summary()
        assert live["pages_demoted"] > 0, "nothing was ever demoted"
        assert live["pages_promoted"] > 0, (
            "warm re-asks never promoted — the ladder was idle")
        assert live["checksum_refusals"] == 0, live
        srv.stop()

        ratio = flat_dt / tiered_dt
        assert ratio >= 1.3, (
            f"tiered warm goodput only {ratio:.2f}x evict-and-recompute "
            f"({cells / tiered_dt:.2f} vs {cells / flat_dt:.2f} p/s)")

        # Kill/restart: the process dies; only the disk dir survives.
        del srv, store
        srv2 = ScoringServer(engine(), "tiered-bench", scfg,
                             tiers=tcfg).start()
        restart = srv2.tiers.summary()
        assert restart["restart_pages_reseeded"] > 0, (
            "restart-warm re-seeded nothing")
        rewarm = one_pass(srv2)
        assert_bitwise("restart-warm", rewarm, base)
        pstats = srv2.engine.prefix_stats
        avoided = pstats.avoided_frac
        srv2.stop()
        assert avoided >= 0.9, (
            f"restart-warm sentinel grid avoided only "
            f"{100 * avoided:.0f}% of prefix prefill tokens")

        return {
            "cells": cells,
            "pool_pages": pool_pages,
            "working_set_x_hbm": round(
                live["pages_demoted"] / pool_pages, 2),
            "goodput_tiered_p_s": round(cells / tiered_dt, 3),
            "goodput_recompute_p_s": round(cells / flat_dt, 3),
            "tiered_vs_recompute": round(ratio, 3),
            "crashed_dispatches": 0,
            "payloads_bitwise": True,
            "pages_demoted": int(live["pages_demoted"]),
            "pages_promoted": int(live["pages_promoted"]),
            "bytes_spilled": int(live["bytes_spilled"]),
            "restart_pages_reseeded": int(
                restart["restart_pages_reseeded"]),
            "restart_avoided_frac": round(avoided, 4),
        }


def _stream_stats_bench(params, cfg, on_accel: bool, tokenizer=None,
                        batches=None, n_boot=300):
    """Streaming-statistics mode: ONE grid swept twice on fresh engines —

    - BASELINE: streaming sink OFF, row artifact ON; "analysis" is the
      pre-tentpole pipeline (read the csv back, rebuild the lattice,
      summarize) — sweep + reload + CIs on the host path.
    - STREAMING: sink ON, row artifact OFF; every dispatch folds on
      device, finalize reads the accumulator once — no per-row payload
      ever crosses to the host (rows_folded == grid size is asserted,
      as is counts/kappa parity between the two paths).

    Returns the "streaming_stats" headline dict: sweep+analysis
    wall-clock both ways, the speedup ratio, rows folded, and the
    host-transferred bytes (csv artifact vs accumulator + the avoided
    per-row payload bytes)."""
    import numpy as np

    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RuntimeConfig
    from lir_tpu.data import schemas
    from lir_tpu.data.prompts import LegalPrompt
    from lir_tpu.engine import grid as grid_mod
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.engine.sweep import run_perturbation_sweep
    from lir_tpu.stats import streaming as st

    if batches is None:
        batches = SWEEP_BATCHES_TPU if on_accel else SWEEP_BATCHES_CPU
    cells = SWEEP_CELLS_TPU if on_accel else 2 * SWEEP_CELLS_CPU
    rng = np.random.default_rng(41)
    if tokenizer is not None:
        from chain7b import (CHAIN_CONFIDENCE_FORMAT, CHAIN_RESPONSE_FORMAT,
                             bucket_sized_words)
        words, n_words = bucket_sized_words(tokenizer, rng)
        response_format = CHAIN_RESPONSE_FORMAT
        confidence_format = CHAIN_CONFIDENCE_FORMAT
    else:
        words = ("coverage policy flood water damage claim insurer "
                 "premium exclusion endorsement peril deductible").split()
        n_words = 170 if on_accel else 12
        response_format = "Respond with either ' Yes' or ' No' only ."
        confidence_format = "Give a confidence number from 0 to 100 ."

    def text():
        return " ".join(rng.choice(words) for _ in range(n_words)) + " ?"

    lp = (LegalPrompt(main=text(), response_format=response_format,
                      target_tokens=("Yes", "No"),
                      confidence_format=confidence_format),)
    perts = ([text() for _ in range(cells - 1)],)
    slot_map = st.slot_map_from_cells(
        grid_mod.build_grid("bench-stream", lp, perts))

    last_oom = None
    for batch in batches:
        def make_engine(streaming: bool):
            return ScoringEngine(
                params, cfg,
                tokenizer if tokenizer is not None else FakeTokenizer(),
                RuntimeConfig(batch_size=batch, max_seq_len=512,
                              streaming_stats=streaming,
                              row_artifact=not streaming))

        try:
            # warmup: the IDENTICAL grid on a throwaway engine, so both
            # timed passes run all-warm (the fold executable is keyed by
            # the lattice shape — a smaller warmup grid would leave its
            # compile inside the streaming window).
            with tempfile.TemporaryDirectory() as td:
                run_perturbation_sweep(make_engine(True), "bench-stream",
                                       lp, perts, Path(td) / "w.csv")

            # BASELINE: csv rows + host reload analysis.
            with tempfile.TemporaryDirectory() as td:
                out = Path(td) / "base.csv"
                t0 = time.perf_counter()
                run_perturbation_sweep(make_engine(False), "bench-stream",
                                       lp, perts, out)
                df = schemas.read_results_frame(out)
                acc_reload = st.accum_from_rows(df, slot_map, 1, cells,
                                                seed=42)
                reloaded = st.summarize(acc_reload, n_boot=n_boot)
                base_s = time.perf_counter() - t0
                csv_bytes = out.stat().st_size

            # STREAMING: device accumulator, no row artifact.
            with tempfile.TemporaryDirectory() as td:
                out = Path(td) / "stream.csv"
                t0 = time.perf_counter()
                engine = make_engine(True)
                run_perturbation_sweep(engine, "bench-stream", lp, perts,
                                       out)
                sink = engine.stream_sink
                streamed = sink.finalize(n_boot=n_boot)
                stream_s = time.perf_counter() - t0
        except Exception as err:  # noqa: BLE001 — OOM falls back
            if _is_oom(err):
                last_oom = err
                continue
            raise
        st.assert_parity(streamed, reloaded)   # counts/kappa bitwise
        counters = sink.stats.summary()
        assert counters["rows_folded"] == cells, counters
        out = {
            "cells": cells, "batch": batch, "n_boot": n_boot,
            "rows_folded_on_device": counters["rows_folded"],
            "dispatch_folds": counters["dispatch_folds"],
            "streaming_sweep_analysis_s": round(stream_s, 3),
            "baseline_sweep_analysis_s": round(base_s, 3),
            "speedup_vs_csv_reload": round(base_s / stream_s, 3),
            "finalize_s": counters["finalize_s"],
            # Host-transfer accounting: what crossed device->host/disk.
            "baseline_row_artifact_bytes": csv_bytes,
            "streaming_accum_bytes": counters["accum_bytes"],
            "host_payload_bytes_avoided": counters["host_bytes_avoided"],
            "parity_ok": True,
        }
        print(f"# streaming stats mode ({cells} cells, batch {batch}): "
              f"sweep+analysis {stream_s:.2f}s streaming vs "
              f"{base_s:.2f}s csv-reload "
              f"({out['speedup_vs_csv_reload']:.2f}x), "
              f"{counters['rows_folded']} rows folded on device, "
              f"{counters['host_bytes_avoided']} payload bytes + "
              f"{csv_bytes} artifact bytes never crossed the host",
              file=sys.stderr)
        return out
    print(f"# streaming stats mode: every batch candidate OOMed; "
          f"last: {last_oom}", file=sys.stderr)
    return None


def _chaos_bench(params, cfg, on_accel: bool, tokenizer=None,
                 batches=None):
    """Chaos mode: ONE grid served closed-loop twice — fault-free, then
    under a seeded transient fault schedule (FaultPlan: Bernoulli
    dispatch faults bounded by max_failures, i.e. a transient outage the
    recovery machinery must outlast, injected UNDER the retry policy so
    recovery is exercised, not bypassed). Reports the robustness
    counters (profiling.FaultStats) and goodput-under-faults vs
    fault-free goodput: the price of self-healing, tracked like perf.

    Every request must still resolve "ok" — the fault schedule is
    transient by construction, so a lost or errored request is a
    recovery bug, not chaos."""
    import numpy as np

    from lir_tpu import faults
    from lir_tpu.backends.fake import FakeTokenizer
    from lir_tpu.config import RetryConfig, RuntimeConfig, ServeConfig
    from lir_tpu.engine.runner import ScoringEngine
    from lir_tpu.serve import ScoringServer, ServeRequest

    if batches is None:
        batches = SWEEP_BATCHES_TPU if on_accel else SWEEP_BATCHES_CPU
    cells = 64 if on_accel else SERVE_CELLS_CPU
    rng = np.random.default_rng(29)
    if tokenizer is not None:
        from chain7b import (CHAIN_CONFIDENCE_FORMAT, CHAIN_RESPONSE_FORMAT,
                             bucket_sized_words)
        words, n_words = bucket_sized_words(tokenizer, rng)
        response_format = CHAIN_RESPONSE_FORMAT
        confidence_format = CHAIN_CONFIDENCE_FORMAT
    else:
        words = ("coverage policy flood water damage claim insurer premium "
                 "exclusion endorsement peril deductible").split()
        n_words = 170 if on_accel else VARLEN_WORDS_CPU
        response_format = "Respond with either ' Yes' or ' No' only ."
        confidence_format = "Give a confidence number from 0 to 100 ."

    def text():
        return " ".join(rng.choice(words) for _ in range(n_words)) + " ?"

    texts = [text() for _ in range(cells)]
    serve_cfg = ServeConfig(
        queue_depth=cells + 8, classes=(("chaos", 3600.0),),
        default_class="chaos", linger_s=0.005,
        # Short retries: the chaos bill should be recovery work, not
        # backoff sleeps sized for a real device outage.
        retry=RetryConfig(max_retries=2, initial_delay=0.02,
                          max_delay=0.2, backoff_factor=2.0,
                          full_jitter=True, max_elapsed=5.0),
        breaker_cooldown_s=1.0)

    def request(i, rid):
        return ServeRequest(
            binary_prompt=f"{texts[i]} {response_format}",
            confidence_prompt=f"{texts[i]} {confidence_format}",
            klass="chaos", request_id=rid)

    last_oom = None
    for batch in batches:
        def make_engine():
            return ScoringEngine(params, cfg,
                                 tokenizer if tokenizer is not None
                                 else FakeTokenizer(),
                                 RuntimeConfig(batch_size=batch,
                                               max_seq_len=512,
                                               # Tight watchdog so the
                                               # injected hang below is
                                               # stalled-out in bench
                                               # time, not 30s floors.
                                               watchdog_multiple=4.0,
                                               watchdog_floor_s=0.5))

        def one_session(schedules, guard_schedules=None):
            server = ScoringServer(make_engine(), "bench-chaos",
                                   serve_cfg)
            if schedules is not None:
                # Share the server's FaultStats so injected and
                # recovered counters land in ONE summary.
                plan = faults.FaultPlan(seed=17, schedules=schedules,
                                        stats=server.faults)
                faults.wrap_server(server, plan)
            if guard_schedules is not None:
                # Silent-failure injections (hang/nan) ride a second
                # wrap so they compose with the transient schedule.
                gplan = faults.FaultPlan(seed=19,
                                         schedules=guard_schedules,
                                         stats=server.faults)
                faults.wrap_server(server, gplan)
            server.start()
            # warm pass: compile every shape outside the timed window
            warm = [server.submit(request(i, f"w{i}"))
                    for i in range(min(cells, 2 * batch))]
            for f in warm:
                f.result(timeout=600)
            t0 = time.perf_counter()
            futs = [server.submit(request(i, f"t{i}"))
                    for i in range(cells)]
            out = [f.result(timeout=600) for f in futs]
            dt = time.perf_counter() - t0
            server.stop()
            return server, out, dt

        try:
            _, clean_out, clean_dt = one_session(None)
            server, fault_out, fault_dt = one_session(
                {"dispatch": faults.SiteSchedule(
                    rate=0.25, max_failures=max(2, cells // 8))},
                # Silent faults for the guard layer: one hang the
                # watchdog must stall-out (the warm pass calibrates it)
                # and one NaN row the numerics guard must quarantine.
                guard_schedules={"dispatch": faults.SiteSchedule(
                    fail_calls=(3,), kind="hang", hang_s=30.0)})
            # The NaN injection runs in its own short session so the
            # quarantined request is identifiable (recovery cannot —
            # and must not — resurrect a corrupted row to "ok").
            nan_server = ScoringServer(make_engine(), "bench-chaos",
                                       serve_cfg)
            faults.wrap_server(nan_server, faults.FaultPlan(
                seed=23, schedules={"dispatch":
                                    faults.SiteSchedule.nan_at(
                                        0, rows=(0,))},
                stats=nan_server.faults))
            nan_server.start()
            nan_out = [f.result(timeout=600) for f in
                       [nan_server.submit(request(i % cells, f"q{i}"))
                        for i in range(batch)]]
            nan_server.stop()
        except Exception as err:  # noqa: BLE001 — OOM falls back
            if _is_oom(err):
                last_oom = err
                continue
            raise
        quarantined = [r.request_id for r in nan_out
                       if r.status == "error" and "numerics" in r.note]
        bad = [r.request_id for r in clean_out + fault_out
               if r.status != "ok"]
        bad += [r.request_id for r in nan_out
                if r.status != "ok" and r.request_id not in quarantined]
        if bad:
            print(f"# chaos bench: requests not recovered to ok: {bad}",
                  file=sys.stderr)
        fstats = server.faults
        gstats = server.engine.guard_stats
        nstats = nan_server.engine.guard_stats
        out = {
            "cells": cells, "batch": batch,
            "injected_faults": fstats.injected_total,
            "recovered_dispatches": fstats.recovered_dispatches,
            "degraded_dispatches": fstats.degraded_dispatches,
            "degraded_rows": fstats.degraded_rows,
            "breaker_opens": fstats.breaker_opens,
            "stalls_detected": gstats.stalls_total + nstats.stalls_total,
            "rows_quarantined": (gstats.quarantined_total
                                 + nstats.quarantined_total),
            "inflight_cancelled": (gstats.inflight_cancelled
                                   + nstats.inflight_cancelled),
            "unrecovered_requests": len(bad),
            "goodput_clean_p_s": round(cells / clean_dt, 3),
            "goodput_faults_p_s": round(cells / fault_dt, 3),
            "goodput_vs_clean": round(clean_dt / fault_dt, 3),
        }
        print(f"# chaos mode ({cells} reqs, {fstats.injected_total} "
              f"injected faults): goodput {out['goodput_faults_p_s']:.3f} "
              f"p/s under faults vs {out['goodput_clean_p_s']:.3f} clean "
              f"({out['goodput_vs_clean']:.2f}x), recovered "
              f"{fstats.recovered_dispatches} dispatches, degraded "
              f"{fstats.degraded_rows} rows, stalled-out "
              f"{out['stalls_detected']}, quarantined "
              f"{out['rows_quarantined']}", file=sys.stderr)
        return out
    print(f"# chaos mode: every batch candidate OOMed; last: {last_oom}",
          file=sys.stderr)
    return None


if __name__ == "__main__":
    main()
