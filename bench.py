"""Benchmark: prompts/sec/chip on the perturbation-sweep scoring path.

BASELINE.json's metric. The reference's "throughput" was the OpenAI Batch API
(server-side, 24 h completion window — no local number exists, so
``vs_baseline`` is measured against the committed nominal in BENCH_NOMINAL
below; >1.0 means faster than the first recorded run of this same bench).

Runs the real engine end to end on whatever accelerator is present (TPU chip
under axon; CPU otherwise): flagship-class decoder, random bf16 weights,
batched greedy decode (10 new tokens — the C13 scan window) + yes/no readout.
Prints ONE JSON line.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# First recorded value of this benchmark on the target chip (v5e-1, 2026-07-29:
# 6554 prompts/s, flagship cfg, seq 256, 10 generated tokens, batch 32 with
# the full-logit-capture decode). The task definition is unchanged — score
# prompts at seq 256 with a 10-token readout window — and vs_baseline tracks
# total framework improvement since that first recording (fused in-scan
# readout + batch scaling). Update deliberately, never silently.
BENCH_NOMINAL = 6554.0  # prompts/sec/chip

# Largest batch first; on HBM exhaustion the bench falls back down the list
# (batch 512 fits the flagship bench config on v5e-1 with ~2 GB headroom).
BATCH_CANDIDATES = (512, 256, 64, 32)
SEQ = 256
NEW_TOKENS = 10  # MAX_LOOK_AHEAD: the positions the C13 readout consumes


def main() -> None:
    from __graft_entry__ import _flagship_cfg
    from lir_tpu.engine import generate, score
    from lir_tpu.models import decoder

    cfg = _flagship_cfg()
    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32

    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=dtype)
    rng = np.random.default_rng(0)
    digit_ids = jnp.arange(10, 110, dtype=jnp.int32)
    digit_vals = jnp.arange(0, 100, dtype=jnp.float32)

    def run_at(batch: int) -> float:
        toks = jnp.asarray(
            rng.integers(3, cfg.vocab_size, (batch, SEQ)), jnp.int32)
        mask = jnp.ones_like(toks)
        yes_ids = jnp.full((batch,), 1, jnp.int32)
        no_ids = jnp.full((batch,), 2, jnp.int32)

        def step(params, toks, mask):
            # The production scoring path: fused in-scan readout (no
            # (B, T, V) logit stack leaves the device).
            fused = generate.greedy_decode_fused(
                params, cfg, toks, mask, yes_ids, no_ids, digit_ids,
                digit_vals, max_new_tokens=NEW_TOKENS)
            return score.readout_from_fused(fused, yes_ids, no_ids)

        jax.block_until_ready(step(params, toks, mask))  # warmup/compile
        n_iters = max(4, 2560 // batch)
        # Best of 3 trials: the tunneled-TPU dispatch path has run-to-run
        # contention jitter; peak throughput is the stable quantity.
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n_iters):
                jax.block_until_ready(step(params, toks, mask))
            best = max(best, batch * n_iters / (time.perf_counter() - t0))
        return best

    prompts_per_sec = 0.0
    batch_used = BATCH_CANDIDATES[-1]
    for batch in BATCH_CANDIDATES:
        if not on_tpu and batch > 64:
            continue  # CPU smoke runs stay small
        try:
            prompts_per_sec = run_at(batch)
            batch_used = batch
            break
        except Exception:
            continue  # HBM exhaustion at this batch: fall back

    print(json.dumps({
        "metric": "prompts_per_sec_per_chip",
        "value": round(prompts_per_sec, 3),
        "unit": (f"prompts/s ({cfg.name}, seq={SEQ}, {NEW_TOKENS} gen, "
                 f"batch={batch_used}, {dev.platform})"),
        "vs_baseline": round(prompts_per_sec / BENCH_NOMINAL, 3),
    }))


if __name__ == "__main__":
    main()
